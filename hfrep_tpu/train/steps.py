"""Jitted alternating G/D train steps for the three loss families.

The reference's hot loop (``GAN/MTSS_WGAN_GP.py:260-284``) rebuilds batch
indices, noise, and ground-truth tensors in host numpy every step and
launches 6 separate Keras graph executions per epoch — 5000 × 6 host→
device round trips.  Here one *epoch* (n_critic critic updates + one
generator update) is a single jitted function with on-device PRNG; on top
of that :func:`make_multi_step` scans ``steps_per_call`` epochs into one
XLA program, so the host loop dispatches ~40× fewer calls.

Loss semantics, derived from (not translated from) the reference graphs:

* **bce** (GAN / MTSS-GAN, ``GAN/GAN.py:160-204``): two *sequential*
  discriminator Adam updates per epoch — real batch vs label 1, then a
  freshly generated batch vs label 0 (two ``train_on_batch`` calls = two
  optimizer steps, not one averaged step) — then one generator update
  against label 1 on fresh noise.  D emits per-timestep logits (B, W, 1);
  the scalar label broadcasts over W exactly as Keras broadcasts targets.

* **wgan_clip** (WGAN / MTSS-WGAN, ``GAN/WGAN.py:168-212``): n_critic=5
  inner iterations, each doing two sequential critic updates
  (mean(−c(real)) then mean(+c(fake))) followed by a hard clip of *every*
  critic tensor to ±0.01 — including LayerNorm scales, faithfully to the
  reference's per-layer ``get_weights/np.clip/set_weights`` round-trip
  (``GAN/WGAN.py:195-199``), which here is a free `tree_map` instead of
  the repo's single worst host↔device crossing.  The generator update
  reuses the *last* critic-iteration noise (``GAN/WGAN.py:203``).

* **wgan_gp** (WGAN-GP / MTSS-WGAN-GP, ``GAN/MTSS_WGAN_GP.py:254-284``):
  n_critic iterations of a single RMSprop update on the summed 3-term
  loss mean(−c(real)) + mean(c(fake)) + 10·mean((1−‖∇_x̂ c(x̂)‖)²) with
  x̂ = α·real + (1−α)·fake — the Keras graph's loss_weights=[1,1,10]
  with ±1 dummy targets collapses to exactly this scalar.  The gradient
  penalty is an exact `jax.grad` w.r.t. the interpolates (the reference
  needed TF1 ``K.gradients`` graph surgery).  α is drawn per *sample*
  (B, 1, 1), fixing the reference's hard-coded batch-32 α shape
  (``GAN/MTSS_WGAN_GP.py:198``).

Parallel execution is layout, not semantics: the mesh launch path
(:mod:`hfrep_tpu.parallel.rules`) runs this very step as a GLOBAL
program under ``pjit`` — the optional ``shard_data`` hook annotates the
sampled batch/noise/α tensors with sharding constraints and GSPMD
derives every collective.  With the hook absent (the default) the
traced program is the literal single-device graph.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from hfrep_tpu.config import TrainConfig
from hfrep_tpu.models.registry import GanPair
from hfrep_tpu.obs import health as health_mod
from hfrep_tpu.train.states import GanState, make_optimizers

Metrics = dict


def _health_metrics(state0: GanState, state1: GanState, g_grads,
                    d_gn_sq, losses) -> Metrics:
    """The in-graph health block every step family shares (built only
    when :func:`hfrep_tpu.obs.health.active` — the step's traced graph is
    otherwise the literal pre-health program).  All outputs are pure
    functions of values the step already computed, so enabling health
    cannot perturb the training trajectory (pinned); they ride the
    existing metrics dict to the host at the block boundaries the
    trainer already syncs at — zero additional device→host syncs.

    ``d_gn_sq`` is the critic phase's (last-iteration) grad sq-norm,
    ``g_grads`` the generator update's gradient pytree, ``losses`` the
    scalar losses whose nonfiniteness should count toward the tripwire
    even when the parameters are still finite (a NaN loss poisons the
    NEXT update)."""
    params1 = {"g": state1.g_params, "d": state1.d_params}
    nonfinite = (health_mod.tree_nonfinite(params1)
                 + sum(jnp.sum((~jnp.isfinite(
                     jnp.asarray(v, jnp.float32))).astype(jnp.float32))
                       for v in losses))
    return {
        "health_g_grad_norm": jnp.sqrt(health_mod.tree_sq_norm(g_grads)),
        "health_d_grad_norm": jnp.sqrt(d_gn_sq),
        "health_update_norm": jnp.sqrt(
            health_mod.tree_update_sq_norm(
                {"g": state0.g_params, "d": state0.d_params}, params1)),
        "health_param_norm": health_mod.tree_norm(params1),
        "health_nonfinite": nonfinite,
    }


def _bce_logits(logits: jnp.ndarray, label: float) -> jnp.ndarray:
    """Binary cross-entropy from logits against a constant broadcast label."""
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, jnp.full_like(logits, label)))


def _sample_real(key, dataset: jnp.ndarray, batch: int) -> jnp.ndarray:
    idx = jax.random.randint(key, (batch,), 0, dataset.shape[0])
    return jnp.take(dataset, idx, axis=0)


def gradient_penalty(d_apply: Callable, d_params, interp: jnp.ndarray) -> jnp.ndarray:
    """mean((1 − ‖∇_x̂ c(x̂)‖)²) over the batch of interpolates.

    Exact-gradient port of ``gradient_penalty_loss``
    (``GAN/MTSS_WGAN_GP.py:201-216``): per-sample L2 norm over all
    non-batch axes of the critic's input gradient at x̂.

    The norm and its reduction accumulate in float32 regardless of the
    critic's compute dtype: the score sum driving the input gradient and
    the gradient itself are cast up before any reduction.  Both casts
    are identities on the fp32 policy (``convert_element_type`` to the
    operand's own dtype inserts nothing), so the fp32 graph is unchanged
    — on a bf16 policy they are what keeps the penalty's second-order
    signal out of bf16's 8-bit mantissa.

    Under the mesh launch path the interpolates inherit the sampled
    tensors' dp/sp sharding constraints and GSPMD transposes the
    partitioned second-order path automatically — no manual collective
    reasoning survives here (it used to; see the git history of the
    shard_map-era dp×sp region).
    """
    grads = jax.grad(
        lambda x: jnp.sum(d_apply(d_params, x).astype(jnp.float32)))(interp)
    grads = grads.astype(jnp.float32)
    norms = jnp.sqrt(jnp.sum(grads**2, axis=tuple(range(1, grads.ndim))) + 1e-12)
    return jnp.mean((1.0 - norms) ** 2)


def resolve_lstm_backend(choice: str) -> str:
    """'auto' → pallas on a real TPU, xla elsewhere (interpret-mode pallas
    is orders of magnitude slower than the scan on CPU)."""
    if choice == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if choice not in ("pallas", "xla"):
        raise ValueError(f"lstm_backend must be auto|pallas|xla, got {choice!r}")
    return choice


def make_train_step(pair: GanPair, tcfg: TrainConfig, dataset: jnp.ndarray,
                    apply_fns: Optional[Tuple[Callable, Callable]] = None,
                    shard_data: Optional[Callable] = None) -> Callable[[GanState, jax.Array], Tuple[GanState, Metrics]]:
    """Build ``step(state, key) -> (state, metrics)`` for one epoch.

    ``apply_fns=(g_apply, d_apply)`` overrides how the generator/critic
    are evaluated while keeping every other step semantic (sampling
    streams, critic loop, GP, optimizer updates) — how the layer
    pipeline reuses this machinery with depth-split forward passes
    (:func:`hfrep_tpu.parallel.layer_pipeline.make_pp_train_step`).

    ``shard_data`` (:func:`hfrep_tpu.parallel.rules.data_constraint`) is
    the mesh launch path's LAYOUT hook: ``shard_data(x, batch_axis)``
    annotates each sampled batch/noise/α tensor with a sharding
    constraint so GSPMD splits the batch over ``dp`` (and the window
    over ``sp``) — values are untouched, every epoch still consumes the
    exact single-device sample stream, which is why a mesh run follows
    the single-device trajectory at the same global batch and key.
    ``None`` (the default) traces the literal single-device program.
    """
    g_tx, d_tx = make_optimizers(pair, tcfg)
    # Flight-recorder health (hfrep_tpu/obs/health.py): decided at BUILD
    # time — None (the default) traces the literal pre-health program, so
    # the fp32 jaxpr pins hold by construction; a config adds grad/
    # update/param-norm + nonfinite outputs to the metrics dict only.
    hcfg = health_mod.active()
    # Mixed-precision posture (hfrep_tpu/core/precision.py): modules cast
    # fp32 master weights + inputs to the compute dtype internally; here
    # `acc` lifts critic scores/logits back to float32 BEFORE any loss
    # reduction so means/sums never accumulate in bf16, which also makes
    # every gradient a float32 cotangent of float32 params — optimizer
    # state stays fp32 end to end.  On the default fp32 policy `acc` is
    # the literal identity and the traced graph is unchanged (pinned).
    acc = pair.policy.accum
    # Every site — including the gradient penalty's second-order
    # ∂/∂θ ∇_x c path — runs the resolved backend: the pallas LSTM is
    # twice-differentiable end to end (nested custom_vjps with a
    # hand-derived adjoint kernel, hfrep_tpu/ops/pallas_lstm.py, tested
    # against the XLA double backward).
    if apply_fns is not None:
        g_apply, d_apply = apply_fns
    else:
        be = resolve_lstm_backend(tcfg.lstm_backend)
        g_apply = lambda p, z, backend=be: pair.generator.apply({"params": p}, z, backend=backend)
        d_apply = lambda p, x, backend=be: pair.discriminator.apply({"params": p}, x, backend=backend)
    batch = tcfg.batch_size
    window, features = dataset.shape[1], dataset.shape[2]
    noise_shape = (batch, window, features)

    def _hint(x, batch_axis: int = 0):
        """The mesh layout hook — the literal identity when no mesh is
        launching this step (shard_data None), so the default jaxpr is
        the exact single-device program (pinned)."""
        return x if shard_data is None else shard_data(x, batch_axis)

    def _real(key):
        return _hint(_sample_real(key, dataset, batch))

    def _noise(key):
        return _hint(jax.random.normal(key, noise_shape))

    def _alpha(key):
        return _hint(jax.random.uniform(key, (batch, 1, 1)))

    def _loop_init(key):
        """Initial d_loss carry for the critic fori_loops."""
        del key
        return jnp.zeros(())

    def d_update(d_params, d_opt, loss_fn):
        """Returns ``(params, opt, loss, aux, grads)`` — the gradient
        pytree rides along for the (build-time-gated) health block; when
        health is off nothing consumes it and XLA's DCE sees the exact
        pre-health graph (the grads already exist for the update)."""
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(d_params)
        updates, d_opt = d_tx.update(grads, d_opt, d_params)
        return optax.apply_updates(d_params, updates), d_opt, loss, aux, grads

    def g_update(state: GanState, loss_fn):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.g_params)
        updates, g_opt = g_tx.update(grads, state.g_opt, state.g_params)
        return state.replace(g_params=optax.apply_updates(state.g_params, updates),
                             g_opt=g_opt, step=state.step + 1), loss, grads

    # ------------------------------------------------------------------ bce
    def bce_step(state: GanState, key: jax.Array):
        k_idx, k_z1, k_z2 = jax.random.split(key, 3)
        real = _real(k_idx)
        fake = g_apply(state.g_params, _noise(k_z1))

        def loss_real(p):
            logits = acc(d_apply(p, real))
            return _bce_logits(logits, 1.0), jnp.mean((logits > 0).astype(jnp.float32))

        def loss_fake(p):
            logits = acc(d_apply(p, lax.stop_gradient(fake)))
            return _bce_logits(logits, 0.0), jnp.mean((logits <= 0).astype(jnp.float32))

        state0 = state
        d_params, d_opt, l_real, acc_r, gr1 = d_update(
            state.d_params, state.d_opt, loss_real)
        d_params, d_opt, l_fake, acc_f, gr2 = d_update(
            d_params, d_opt, loss_fake)
        state = state.replace(d_params=d_params, d_opt=d_opt)

        def loss_g(p):
            return _bce_logits(acc(d_apply(state.d_params, g_apply(p, _noise(k_z2)))), 1.0), None

        state, g_loss, g_grads = g_update(state, loss_g)
        metrics = {"d_loss": 0.5 * (l_real + l_fake),
                   "d_acc": 0.5 * (acc_r + acc_f), "g_loss": g_loss}
        if hcfg:
            metrics.update(_health_metrics(
                state0, state, g_grads,
                health_mod.tree_sq_norm(gr1) + health_mod.tree_sq_norm(gr2),
                (l_real, l_fake, g_loss)))
        return state, metrics

    # ------------------------------------------------------------ wgan_clip
    clip = tcfg.clip_value

    def _critic_loop_inputs(key, g_params, with_alpha: bool):
        """Everything the n_critic loop consumes that does not depend on
        the loop carry, hoisted out of it.

        The generator parameters are constant across the critic
        iterations (only d_params/d_opt update inside), so the n_critic
        fake batches are ONE (n_critic·B)-row generator traversal instead
        of n_critic sequential ones — per-sample math and the
        per-iteration RNG streams are unchanged (the keys are derived
        exactly as the loop derived them, just precomputed), but
        n_critic−1 serial LSTM scans leave the critical path and the one
        that remains runs at n_critic× the MXU row occupancy.
        """
        # 2-way vs 3-way split preserves each family's exact RNG streams
        # (wgan drew k_idx, k_z; wgan_gp drew k_idx, k_z, k_a).
        ks = [jax.random.split(jax.random.fold_in(key, i), 3 if with_alpha else 2)
              for i in range(tcfg.n_critic)]
        k_idx = jnp.stack([k[0] for k in ks])
        noises = _hint(jnp.stack([_noise(k[1]) for k in ks]),
                       batch_axis=1)                     # (n_critic, B, W, F)
        n, b = noises.shape[0], noises.shape[1]
        fakes = lax.stop_gradient(
            g_apply(g_params, _hint(noises.reshape(n * b, window, features)))
        ).reshape(noises.shape)
        alphas = (_hint(jnp.stack([_alpha(k[2]) for k in ks]), batch_axis=1)
                  if with_alpha else None)
        return k_idx, noises, fakes, alphas

    # A size-1 critic "loop" lowers to an XLA while op — a scheduling
    # barrier the compiler can neither fuse nor software-pipeline across,
    # for a loop that cannot iterate.  With ``tcfg.fuse_gd`` (default)
    # the n_critic == 1 step instead emits the critic update and the
    # generator update as ONE straight-line computation: identical ops in
    # identical order (the loop body inlined at i=0), pinned equivalent
    # by tests/test_precision.py.  n_critic > 1 keeps the loop — the
    # d_params carry chain is inherently serial.
    fuse_single = tcfg.n_critic == 1 and tcfg.fuse_gd

    def _critic_phase(state: GanState, key, critic_iter):
        """d-phase dispatch shared by the two Wasserstein steps: the
        straight-line fused form when n_critic allows, the fori_loop
        otherwise.  ``critic_iter(i, (d_params, d_opt, d_loss))`` is the
        unchanged per-iteration body; with health on the carry grows a
        4th element — the iteration's critic grad sq-norm."""
        init = (state.d_params, state.d_opt, _loop_init(key))
        if hcfg:
            init = init + (_loop_init(key),)
        if fuse_single:
            return critic_iter(0, init)
        return lax.fori_loop(0, tcfg.n_critic, critic_iter, init)

    def wgan_step(state: GanState, key: jax.Array):
        k_idx, noises, fakes, _ = _critic_loop_inputs(key, state.g_params, False)

        def critic_iter(i, carry):
            d_params, d_opt = carry[0], carry[1]
            real = _real(k_idx[i])
            fake = fakes[i]

            def loss_real(p):
                return jnp.mean(-acc(d_apply(p, real))), None

            def loss_fake(p):
                return jnp.mean(acc(d_apply(p, fake))), None

            d_params, d_opt, l_real, _, gr1 = d_update(d_params, d_opt, loss_real)
            d_params, d_opt, l_fake, _, gr2 = d_update(d_params, d_opt, loss_fake)
            d_params = jax.tree_util.tree_map(lambda w: jnp.clip(w, -clip, clip), d_params)
            out = (d_params, d_opt, 0.5 * (l_real + l_fake))
            if hcfg:        # last iteration's critic grad sq-norm wins
                out = out + (health_mod.tree_sq_norm(gr1)
                             + health_mod.tree_sq_norm(gr2),)
            return out

        phase = _critic_phase(state, key, critic_iter)
        d_params, d_opt, d_loss = phase[0], phase[1], phase[2]
        state0 = state
        state = state.replace(d_params=d_params, d_opt=d_opt)

        def loss_g(p):
            # reference reuses the final critic-loop noise (GAN/WGAN.py:203)
            return jnp.mean(-acc(d_apply(state.d_params, g_apply(p, noises[-1])))), None

        state, g_loss, g_grads = g_update(state, loss_g)
        metrics = {"d_loss": d_loss, "g_loss": g_loss}
        if hcfg:
            metrics.update(_health_metrics(state0, state, g_grads, phase[3],
                                           (d_loss, g_loss)))
        return state, metrics

    # -------------------------------------------------------------- wgan_gp
    gp_w = tcfg.gp_weight

    def gp_critic_loss(d_params, real, fake, alpha):
        interp = alpha * real + (1.0 - alpha) * fake
        b = real.shape[0]

        # One critic traversal scores real ⊕ fake (2B batch) — identical
        # math to two separate applications since the LSTM recurrence is
        # per-sample, but one fewer serial scan on the critical path.
        # The gradient penalty stays a separate B-wide traversal: folding
        # interp into the batch too would widen the *second-order* path
        # (outer grad through the GP input-grad) to 3B and measures
        # slower on the chip than the scan it saves.
        #
        # The _hint on the concatenated batch is LOAD-BEARING under a
        # mesh with a free (tp) axis on this runtime: XLA's SPMD
        # partitioner computes WRONG critic scores for a concat of two
        # dp-constrained operands unless the concat's own layout is
        # re-pinned (measured 0.24 absolute score error, every row —
        # pinned by tests/test_mesh_rules.py; identity when meshless).
        scores = acc(d_apply(d_params,
                             _hint(jnp.concatenate([real, fake], axis=0))))
        gp = gradient_penalty(d_apply, d_params, interp)
        w_loss = jnp.mean(-scores[:b]) + jnp.mean(scores[b:])
        return w_loss + gp_w * gp, (w_loss, gp)

    def wgan_gp_step(state: GanState, key: jax.Array):
        k_idx, noises, fakes, alphas = _critic_loop_inputs(
            key, state.g_params, True)

        def critic_iter(i, carry):
            d_params, d_opt = carry[0], carry[1]
            real = _real(k_idx[i])

            loss_fn = lambda p: gp_critic_loss(p, real, fakes[i], alphas[i])
            d_params, d_opt, loss, _, grads = d_update(d_params, d_opt, loss_fn)
            out = (d_params, d_opt, loss)
            if hcfg:
                out = out + (health_mod.tree_sq_norm(grads),)
            return out

        phase = _critic_phase(state, key, critic_iter)
        d_params, d_opt, d_loss = phase[0], phase[1], phase[2]
        state0 = state
        state = state.replace(d_params=d_params, d_opt=d_opt)

        def loss_g(p):
            # reference reuses the final critic-loop noise (GAN/MTSS_WGAN_GP.py:281)
            return jnp.mean(-acc(d_apply(state.d_params, g_apply(p, noises[-1])))), None

        state, g_loss, g_grads = g_update(state, loss_g)
        metrics = {"d_loss": d_loss, "g_loss": g_loss}
        if hcfg:
            metrics.update(_health_metrics(state0, state, g_grads, phase[3],
                                           (d_loss, g_loss)))
        return state, metrics

    return {"bce": bce_step, "wgan_clip": wgan_step, "wgan_gp": wgan_gp_step}[pair.loss]


def make_conditional_step(pair: GanPair, tcfg: TrainConfig,
                          dataset: jnp.ndarray,
                          conditions: jnp.ndarray) -> Callable[[GanState, jax.Array], Tuple[GanState, Metrics]]:
    """Conditional (cGAN) epoch step for the scenario factory.

    ``pair`` is a :func:`~hfrep_tpu.models.registry.build_conditional_gan`
    pair whose members take ``(input, cond)``; ``conditions`` is the
    (N, C) per-window condition matrix aligned row-for-row with
    ``dataset`` (:func:`hfrep_tpu.scenario.regimes.window_conditions`).
    Real batches ride with their own condition vectors (one gather
    serves both), fakes are generated — and scored — under the same
    conditions, so the critic only ever compares real and synthetic
    windows *of the same regime*.  Loss semantics per family are the
    unconditional step's; this builder deliberately leaves out the
    mesh/fusion machinery (the scenario drives are single-host by
    design), and the unconditional :func:`make_train_step` is untouched
    — conditioning OFF remains the literal pre-scenario program (pinned
    at jaxpr level by ``tests/test_scenario.py``).
    """
    g_tx, d_tx = make_optimizers(pair, tcfg)
    hcfg = health_mod.active()     # build-time, like the unconditional step
    acc = pair.policy.accum
    be = resolve_lstm_backend(tcfg.lstm_backend)
    conditions = jnp.asarray(conditions, jnp.float32)
    if conditions.ndim != 2 or conditions.shape[0] != dataset.shape[0]:
        raise ValueError(
            f"conditions {conditions.shape} do not align with dataset "
            f"{dataset.shape}: one condition vector per training window")
    g_apply = lambda p, z, c: pair.generator.apply({"params": p}, z, c,
                                                   backend=be)
    d_apply = lambda p, x, c: pair.discriminator.apply({"params": p}, x, c,
                                                       backend=be)
    batch = tcfg.batch_size
    window, features = dataset.shape[1], dataset.shape[2]

    def _real(key):
        idx = jax.random.randint(key, (batch,), 0, dataset.shape[0])
        return (jnp.take(dataset, idx, axis=0),
                jnp.take(conditions, idx, axis=0))

    def _noise(key):
        return jax.random.normal(key, (batch, window, features))

    def d_update(d_params, d_opt, loss_fn):
        loss, grads = jax.value_and_grad(loss_fn)(d_params)
        updates, d_opt = d_tx.update(grads, d_opt, d_params)
        return optax.apply_updates(d_params, updates), d_opt, loss, grads

    def g_update(state: GanState, loss_fn):
        loss, grads = jax.value_and_grad(loss_fn)(state.g_params)
        updates, g_opt = g_tx.update(grads, state.g_opt, state.g_params)
        return state.replace(
            g_params=optax.apply_updates(state.g_params, updates),
            g_opt=g_opt, step=state.step + 1), loss, grads

    def bce_step(state: GanState, key: jax.Array):
        k_idx, k_z1, k_z2 = jax.random.split(key, 3)
        real, cond = _real(k_idx)
        fake = lax.stop_gradient(g_apply(state.g_params, _noise(k_z1), cond))
        state0 = state
        d_params, d_opt, l_real, gr1 = d_update(
            state.d_params, state.d_opt,
            lambda p: _bce_logits(acc(d_apply(p, real, cond)), 1.0))
        d_params, d_opt, l_fake, gr2 = d_update(
            d_params, d_opt,
            lambda p: _bce_logits(acc(d_apply(p, fake, cond)), 0.0))
        state = state.replace(d_params=d_params, d_opt=d_opt)
        state, g_loss, g_grads = g_update(state, lambda p: _bce_logits(
            acc(d_apply(state.d_params, g_apply(p, _noise(k_z2), cond),
                        cond)), 1.0))
        metrics = {"d_loss": 0.5 * (l_real + l_fake), "g_loss": g_loss}
        if hcfg:
            metrics.update(_health_metrics(
                state0, state, g_grads,
                health_mod.tree_sq_norm(gr1) + health_mod.tree_sq_norm(gr2),
                (l_real, l_fake, g_loss)))
        return state, metrics

    clip, gp_w = tcfg.clip_value, tcfg.gp_weight

    def _wasserstein_step(state: GanState, key: jax.Array, with_gp: bool):
        def critic_iter(i, carry):
            d_params, d_opt = carry[0], carry[1]
            ki = jax.random.fold_in(key, i)
            k_idx, k_z, k_a = jax.random.split(ki, 3)
            real, cond = _real(k_idx)
            fake = lax.stop_gradient(
                g_apply(state.g_params, _noise(k_z), cond))
            if with_gp:
                alpha = jax.random.uniform(k_a, (batch, 1, 1))
                interp = alpha * real + (1.0 - alpha) * fake

                def loss_fn(p):
                    scores = acc(d_apply(
                        p, jnp.concatenate([real, fake], axis=0),
                        jnp.concatenate([cond, cond], axis=0)))
                    gp = gradient_penalty(
                        lambda pp, x: d_apply(pp, x, cond), p, interp)
                    return (jnp.mean(-scores[:batch])
                            + jnp.mean(scores[batch:]) + gp_w * gp)

                d_params, d_opt, loss, grads = d_update(d_params, d_opt,
                                                        loss_fn)
                gn_sq = health_mod.tree_sq_norm(grads) if hcfg else None
            else:
                d_params, d_opt, l_real, gr1 = d_update(
                    d_params, d_opt,
                    lambda p: jnp.mean(-acc(d_apply(p, real, cond))))
                d_params, d_opt, l_fake, gr2 = d_update(
                    d_params, d_opt,
                    lambda p: jnp.mean(acc(d_apply(p, fake, cond))))
                d_params = jax.tree_util.tree_map(
                    lambda w: jnp.clip(w, -clip, clip), d_params)
                loss = 0.5 * (l_real + l_fake)
                gn_sq = (health_mod.tree_sq_norm(gr1)
                         + health_mod.tree_sq_norm(gr2)) if hcfg else None
            out = (d_params, d_opt, loss)
            if hcfg:
                out = out + (gn_sq,)
            return out

        init = (state.d_params, state.d_opt, jnp.zeros(()))
        if hcfg:
            init = init + (jnp.zeros(()),)
        phase = lax.fori_loop(0, tcfg.n_critic, critic_iter, init)
        d_params, d_opt, d_loss = phase[0], phase[1], phase[2]
        state0 = state
        state = state.replace(d_params=d_params, d_opt=d_opt)
        # the generator trains on the final critic iteration's sampling
        # streams, mirroring the unconditional step's noise reuse
        kl = jax.random.fold_in(key, tcfg.n_critic - 1)
        k_idx, k_z, _ = jax.random.split(kl, 3)
        _, cond_g = _real(k_idx)
        noise_g = _noise(k_z)
        state, g_loss, g_grads = g_update(state, lambda p: jnp.mean(
            -acc(d_apply(state.d_params, g_apply(p, noise_g, cond_g),
                         cond_g))))
        metrics = {"d_loss": d_loss, "g_loss": g_loss}
        if hcfg:
            metrics.update(_health_metrics(state0, state, g_grads, phase[3],
                                           (d_loss, g_loss)))
        return state, metrics

    if pair.loss == "bce":
        return bce_step
    if pair.loss == "wgan_clip":
        return lambda state, key: _wasserstein_step(state, key, False)
    return lambda state, key: _wasserstein_step(state, key, True)


def make_multi_step(pair: GanPair, tcfg: TrainConfig, dataset: jnp.ndarray,
                    jit: bool = True, step: Optional[Callable] = None):
    """Scan ``steps_per_call`` epochs into one compiled program.

    Returns ``fn(state, key) -> (state, stacked_metrics)``; metrics carry
    one entry per inner epoch so per-epoch logging survives the batching.
    ``step`` overrides the epoch step (e.g. a prebuilt mesh-constrained
    or layer-pipelined step) while keeping the scan/key-folding harness
    in one place.
    """
    if step is None:
        step = make_train_step(pair, tcfg, dataset)
    n = tcfg.steps_per_call

    def multi(state: GanState, key: jax.Array):
        def body(carry, i):
            st, m = step(carry, jax.random.fold_in(key, i))
            return st, m

        return lax.scan(body, state, jnp.arange(n))

    return jax.jit(multi, donate_argnums=(0,)) if jit else multi
