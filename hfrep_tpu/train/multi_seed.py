"""Multi-seed vmapped GAN training: K independent models in ONE program.

Why this exists (RESULTS.md "Absolute performance"): at the reference's
batch 32 (``GAN/MTSS_WGAN_GP.py:97-101``) the recurrent matmul occupies
32 of the MXU's 128 systolic rows, and the measured per-sample
throughput at batch 128 is 1.82× batch 32.  The reference's semantics
pin batch 32 per model — but nothing pins *one model per program*.
``jax.vmap`` over the complete train step stacks K independent
members' (32, ·) matmuls into (K·32, ·) MXU work while every member
consumes exactly the PRNG streams of a standalone run: member k's
trajectory equals ``GanTrainer`` seeded with ``seeds[k]`` to summation
round-off — ≤1e-8 after 7 epochs; vmap only reorders the batched
reductions' accumulation
(tests/test_train.py::test_multi_seed_bitwise_equivalence).

This converts the documented roofline headroom into delivered
throughput for the repo's own multi-seed workloads (seed-variance
studies, family evaluation, GAN-augmentation ensembles) without
touching reference semantics.  Measured on chip:
``tools/bench_multi_seed.py`` → RESULTS.md "Multi-seed vmapped
training" — a NEGATIVE throughput result for vmap (distinct per-member
weights can't row-pack the MXU), whose structural fix is
:func:`make_seed_sharded_step`: one member per device on a ``('seed',)``
mesh, linear aggregate scaling by construction
(``MultiSeedTrainer(..., mesh="auto")``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hfrep_tpu import resilience
from hfrep_tpu.config import ExperimentConfig
from hfrep_tpu.core.data import GanDataset
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.train.states import GanState, init_gan_state
from hfrep_tpu.train.steps import make_multi_step, make_train_step


def init_multi_seed_states(init_keys: jnp.ndarray, mcfg, tcfg, pair=None):
    """Stacked ``GanState`` (leading axis = member); member k equals
    ``init_gan_state(init_keys[k], ...)``."""
    if pair is None:
        pair = build_gan(mcfg)
    return jax.vmap(lambda k: init_gan_state(k, mcfg, tcfg, pair))(init_keys)


def make_multi_seed_step(pair, tcfg, dataset: jnp.ndarray, jit: bool = True):
    """``fn(states, keys) -> (states, metrics)`` running one
    ``steps_per_call``-epoch block for every member; ``states`` is a
    stacked pytree and ``keys`` is (K, 2).  The dataset is closed over
    and shared (read-only) across members — each member samples its own
    batches from it with its own key, exactly as a standalone run does."""
    multi = make_multi_step(pair, tcfg, dataset, jit=False)
    fn = jax.vmap(multi)
    return jax.jit(fn, donate_argnums=(0,)) if jit else fn


def make_seed_sharded_step(pair, tcfg, dataset: jnp.ndarray, mesh, jit: bool = True):
    """The structural fix round 3's negative result implies: members don't
    share weights, so put one member per DEVICE instead of row-packing
    them into one device's MXU.

    ``jax.vmap`` packs members' batch rows into wider matmuls — which
    cannot help when each member multiplies a *distinct* weight matrix
    (the measured 0.21×-per-model result, RESULTS.md "Multi-seed vmapped
    training").  ``shard_map`` over a ``('seed',)`` mesh is exactly the
    tool vmap isn't: each device holds its own member's weights and runs
    the unmodified per-member program, so aggregate multi-seed throughput
    scales linearly in devices *by construction* — there is no
    cross-member arithmetic, no collective, nothing to contend on.  (On
    this host's single chip there is nothing to measure — the expected
    pod scaling is linear and is stated, not claimed measured;
    member-exactness versus the standalone trainer is what the virtual
    8-device mesh pins, tests/test_train.py.)

    ``K`` (the stacked leading axis) must be a multiple of the mesh size;
    K/n_dev members run vmapped WITHIN each device (the K == n_dev case
    is a size-1 vmap — arithmetically the standalone program).
    """
    return _seed_shard(make_multi_step(pair, tcfg, dataset, jit=False),
                       mesh, jit)


def _seed_shard(step, mesh, jit: bool = True):
    """Launch a per-member ``step(state, key)`` with the stacked member
    axis sharded over the ``('seed',)`` mesh — the member axis is purely
    spatial (no collectives), so the wrapper is the same for a
    multi-epoch block and a single epoch (the trainer's remainder path
    must shard the RAW step, not a steps_per_call=1 block: the block
    scan folds the key per epoch, a different stream than the standalone
    remainder epoch consumes).

    Since the mesh refactor (ROADMAP item 1) this is the unified pjit
    launch — ``vmap`` over members with the leading axis
    sharding-pinned, GSPMD placing K/n members per device — and it runs
    on every JAX version (the old ``shard_map`` region was dead on this
    image's jax)."""
    from hfrep_tpu.parallel.rules import mesh_launch
    (axis,) = mesh.axis_names

    fn = jax.vmap(step)
    if not jit:
        return fn
    return mesh_launch(fn, mesh,
                       in_specs=(P(axis), P(axis)),
                       out_specs=(P(axis), P(axis)),
                       donate_argnums=(0,))


class MultiSeedTrainer:
    """K member-exact :class:`~hfrep_tpu.train.trainer.GanTrainer` runs
    in one jitted program.

    Mirrors the trainer's key discipline — member k starts from
    ``PRNGKey(seeds[k])``, splits once for (run, init), then splits the
    run key per block — so each member's parameter trajectory equals
    ``GanTrainer`` with ``train.seed = seeds[k]`` (same sample/noise/α
    streams; only reduction-order round-off differs).

    Since ISSUE 5 this trainer carries the same preemption story as
    :class:`~hfrep_tpu.train.trainer.GanTrainer` (a K-seed study is K×
    the work to lose): periodic crash-consistent checkpoints of the
    stacked state + per-member keys (``train.checkpoint_dir`` /
    ``checkpoint_every`` / ``checkpoint_keep``), checksum-verified
    restore with fallback to the previous good checkpoint, and a SIGTERM
    handler that drains at a block boundary — final checkpoint, then
    :class:`~hfrep_tpu.resilience.Preempted` — instead of dying
    mid-write.  The logging pipeline remains the single-model trainer's
    job.
    """

    def __init__(self, cfg: ExperimentConfig, dataset: GanDataset | jnp.ndarray,
                 seeds: Sequence[int], mesh=None):
        """``mesh`` selects the member-parallel execution:

        * ``None`` (default) — vmap row-packing on one device (the
          measured-negative-throughput mode; kept as the single-device
          behavior and the fallback when no usable seed mesh exists).
        * a 1-D ``('seed',)`` :class:`jax.sharding.Mesh` — one member
          (or K/n) per device via :func:`make_seed_sharded_step`.
        * ``"auto"`` — single-process hosts only: seed-sharded over the
          largest mesh size n > 1 with ``K % n == 0`` and n ≤ devices
          (K/n members vmapped within each device), else vmap.  On a
          multi-process pod auto stays vmap — this trainer's states are
          host-local arrays, so a process-spanning mesh must be the
          caller's explicit, ``replicate_to_global``-style decision.
        """
        self.cfg = cfg
        self.seeds = tuple(seeds)
        self.windows = (dataset.windows if isinstance(dataset, GanDataset)
                        else jnp.asarray(dataset))
        self.scaler = dataset.scaler if isinstance(dataset, GanDataset) else None
        self.pair = build_gan(cfg.model)
        if mesh == "auto":
            mesh = None
            k = len(self.seeds)
            # largest usable seed mesh: K % n == 0 (shard_map requirement),
            # n > 1 (a 1-device mesh is vmap with extra steps); K > devices
            # runs K/n members vmapped within each device.  Single-process
            # only: this trainer holds host-local arrays, so auto must not
            # build a process-spanning mesh behind the caller's back.
            if jax.process_count() == 1:
                n = max((d for d in range(2, min(k, len(jax.devices())) + 1)
                         if k % d == 0), default=0)
                if n:
                    import numpy as np
                    from jax.sharding import Mesh
                    mesh = Mesh(np.asarray(jax.devices()[:n]), ("seed",))
        if mesh is not None and len(self.seeds) % mesh.devices.size:
            raise ValueError(
                f"{len(self.seeds)} members not divisible by the "
                f"{mesh.devices.size}-device seed mesh")
        self.mesh = mesh
        base = jnp.stack([jax.random.PRNGKey(s) for s in self.seeds])
        split = jax.vmap(jax.random.split)(base)          # (K, 2, 2)
        self.keys = split[:, 0]                           # per-member run keys
        self.states = init_multi_seed_states(split[:, 1], cfg.model, cfg.train,
                                             self.pair)
        if mesh is not None:
            self._multi = make_seed_sharded_step(self.pair, cfg.train,
                                                 self.windows, mesh)
        else:
            self._multi = make_multi_seed_step(self.pair, cfg.train, self.windows)
        self._one = None
        self._gen = None
        self.epoch = 0

    @property
    def n_seeds(self) -> int:
        return len(self.seeds)

    def _split_keys(self):
        ks = jax.vmap(jax.random.split)(self.keys)
        self.keys = ks[:, 0]
        return ks[:, 1]

    def train(self, epochs: Optional[int] = None):
        from hfrep_tpu.obs import get_obs, mesh_attrs
        obs = get_obs()
        tcfg = self.cfg.train
        spc = tcfg.steps_per_call
        epochs = epochs if epochs is not None else tcfg.epochs
        n_full, remainder = divmod(epochs, spc)
        if obs.enabled:
            obs.event("multi_seed_train_start", members=self.n_seeds,
                      epochs=epochs, mesh=mesh_attrs(self.mesh),
                      mode="seed_sharded" if self.mesh is not None else "vmap",
                      precision=self.pair.policy.describe())
        blocks = obs.counter("multi_seed_blocks")    # no-op when disabled

        def maybe_checkpoint(block_epochs: int) -> None:
            # the modulo only under the full guard: checkpoint_every=0
            # with no checkpoint_dir must keep training, not divide by 0
            if (tcfg.checkpoint_dir and tcfg.checkpoint_every > 0
                    and self.epoch % tcfg.checkpoint_every < block_epochs):
                self.save_checkpoint()
            resilience.tick("block")        # injected faults fire here
            if resilience.drain_requested():
                path = (self.save_checkpoint()
                        if tcfg.checkpoint_dir else None)
                obs.event("preempt_drain", epoch=self.epoch, checkpoint=path)
                raise resilience.Preempted(site="block", epoch=self.epoch,
                                           snapshot=path)

        with resilience.graceful_drain(), \
             obs.span("multi_seed_train", members=self.n_seeds, epochs=epochs):
            for _ in range(n_full):
                self.states, _ = self._multi(self.states, self._split_keys())
                self.epoch += spc
                blocks.inc(member_epochs=self.n_seeds * spc)
                maybe_checkpoint(spc)
            if remainder:
                if self._one is None:
                    step = make_train_step(self.pair, self.cfg.train, self.windows)
                    if self.mesh is not None:
                        self._one = _seed_shard(step, self.mesh)
                    else:
                        self._one = jax.jit(jax.vmap(step), donate_argnums=(0,))
                for _ in range(remainder):
                    self.states, _ = self._one(self.states, self._split_keys())
                    self.epoch += 1
                    maybe_checkpoint(1)
            if obs.enabled:
                # sync before the span closes so it times compute, not the
                # async dispatches the loop queued
                jax.block_until_ready(self.states.g_params)
        if obs.enabled:
            obs.memory_snapshot(phase="multi_seed_train_end")
        return self.states

    # ---------------------------------------------------------- checkpoint
    def _ckpt_tree(self):
        import numpy as np
        return {"states": self.states, "keys": self.keys,
                "epoch": jnp.asarray(self.epoch),
                "seeds": jnp.asarray(np.asarray(self.seeds, np.int64))}

    def save_checkpoint(self, path: Optional[str] = None) -> str:
        """Atomic full-state checkpoint (stacked members + per-member run
        keys + epoch), same crash-consistency contract as the
        single-model trainer's."""
        from hfrep_tpu.obs import get_obs
        from hfrep_tpu.utils import checkpoint as ckpt
        path = path or f"{self.cfg.train.checkpoint_dir}/ckpt_{self.epoch}"
        obs = get_obs()
        with obs.span("checkpoint", epoch=self.epoch, path=str(path)):
            ckpt.save(path, self._ckpt_tree(),
                      metadata={"family": self.cfg.model.family,
                                "epoch": self.epoch, "members": self.n_seeds,
                                "seeds": list(self.seeds)},
                      keep=self.cfg.train.checkpoint_keep)
        obs.counter("checkpoints").inc()
        return path

    def restore_checkpoint(self, path: Optional[str] = None) -> str:
        """Restore ``path`` or the newest good checkpoint in the
        configured dir (corrupt ones are skipped, like the single-model
        trainer); refuses a checkpoint taken with different seeds — the
        member axis would silently mean something else.  Returns the
        path actually restored (≠ the requested one on fallback).  On
        the dir-walking path (``path=None``), when every candidate
        incl. ``.prev`` siblings is corrupt
        (``ckpt_fallback_exhausted``) this returns ``""`` and the
        ensemble starts fresh from its init state instead of wedging;
        an explicitly requested checkpoint still raises."""
        import numpy as np
        from hfrep_tpu.utils import checkpoint as ckpt
        ckpt_dir = self.cfg.train.checkpoint_dir
        if path is not None:
            try:
                restored = ckpt.restore(path, target=self._ckpt_tree())
            except ckpt.CheckpointCorrupt:
                if not ckpt_dir:
                    raise
                restored, path = ckpt.restore_latest_good(
                    ckpt_dir, target=self._ckpt_tree())
        else:
            if not ckpt_dir:
                raise FileNotFoundError("no checkpoint found")
            restored, path = ckpt.restore_latest_good(
                ckpt_dir, target=self._ckpt_tree(), on_exhausted="fresh")
        if restored is None:
            return ""
        saved_seeds = tuple(int(s) for s in np.asarray(restored["seeds"]))
        if saved_seeds != tuple(int(s) for s in self.seeds):
            raise ValueError(
                f"checkpoint {path} holds seeds {saved_seeds}, trainer was "
                f"built with {tuple(self.seeds)}")
        states = jax.tree_util.tree_map(jnp.asarray, restored["states"])
        if not isinstance(states, GanState):
            states = GanState(**{f: restored["states"][f] for f in
                                 ("g_params", "d_params", "g_opt", "d_opt",
                                  "step")})
        self.states = states
        self.keys = jnp.asarray(restored["keys"])
        self.epoch = int(restored["epoch"])
        return str(path)

    def generate(self, key: jax.Array, n_samples: int,
                 unscale: bool = True) -> jnp.ndarray:
        """(K, n, W, F) samples — every member gets the SAME noise (the
        standalone eval protocol fixes the sampling key independently of
        the training seed), so members are comparable pointwise."""
        w, f = self.windows.shape[1], self.windows.shape[2]
        noise = jax.random.normal(key, (n_samples, w, f))
        if self._gen is None:
            from hfrep_tpu.train.steps import resolve_lstm_backend
            be = resolve_lstm_backend(self.cfg.train.lstm_backend)
            self._gen = jax.jit(jax.vmap(
                lambda p, z: self.pair.generator.apply({"params": p}, z,
                                                       backend=be),
                in_axes=(0, None)))
        out = self._gen(self.states.g_params, noise)
        if unscale and self.scaler is not None:
            from hfrep_tpu.core import scaler as mm
            out = jax.vmap(lambda o: mm.inverse_transform(self.scaler, o))(out)
        return out
