"""Training state pytrees and optimizer construction.

Optimizer hyperparameters mirror the reference with Keras's defaults made
explicit: ``Adam(2e-4, beta_1=0.5)`` with eps=1e-7 for the BCE families
(``GAN/GAN.py:100``), ``RMSprop(5e-5)`` with rho=0.9/eps=1e-7 for the
Wasserstein families (``GAN/WGAN.py:99``, ``GAN/MTSS_WGAN_GP.py:128``).
The reference passes one optimizer *object* to two ``compile`` calls,
which in Keras means independent slot variables per model — here that is
simply two independent optax states.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from hfrep_tpu.config import ModelConfig, TrainConfig
from hfrep_tpu.models.registry import GanPair, build_gan


class GanState(flax.struct.PyTreeNode):
    g_params: Any
    d_params: Any
    g_opt: Any
    d_opt: Any
    step: jnp.ndarray


def make_optimizers(pair: GanPair, tcfg: TrainConfig) -> Tuple[optax.GradientTransformation, optax.GradientTransformation]:
    if pair.loss == "bce":
        opt = lambda: optax.adam(tcfg.adam_lr, b1=tcfg.adam_b1, b2=0.999, eps=1e-7)
    else:
        opt = lambda: optax.rmsprop(tcfg.rmsprop_lr, decay=0.9, eps=1e-7)
    return opt(), opt()


def init_conditional_state(key: jax.Array, mcfg: ModelConfig,
                           tcfg: TrainConfig, pair: GanPair,
                           cond_dim: int) -> GanState:
    """:func:`init_gan_state` for a conditional pair: init traces the
    ``(input, cond)`` signature so the first Dense/LSTM layers come up
    ``features + cond_dim`` wide.  Same key discipline (kg for G, kd for
    D) as the unconditional init."""
    kg, kd = jax.random.split(key)
    dummy = jnp.zeros((1, mcfg.window, mcfg.features), jnp.float32)
    cond = jnp.zeros((1, cond_dim), jnp.float32)
    g_params = pair.generator.init(kg, dummy, cond)["params"]
    d_params = pair.discriminator.init(kd, dummy, cond)["params"]
    g_tx, d_tx = make_optimizers(pair, tcfg)
    return GanState(
        g_params=g_params,
        d_params=d_params,
        g_opt=g_tx.init(g_params),
        d_opt=d_tx.init(d_params),
        step=jnp.zeros((), jnp.int32),
    )


def init_gan_state(key: jax.Array, mcfg: ModelConfig, tcfg: TrainConfig,
                   pair: GanPair | None = None) -> GanState:
    if pair is None:
        pair = build_gan(mcfg)
    kg, kd = jax.random.split(key)
    dummy = jnp.zeros((1, mcfg.window, mcfg.features), jnp.float32)
    g_params = pair.generator.init(kg, dummy)["params"]
    d_params = pair.discriminator.init(kd, dummy)["params"]
    g_tx, d_tx = make_optimizers(pair, tcfg)
    return GanState(
        g_params=g_params,
        d_params=d_params,
        g_opt=g_tx.init(g_params),
        d_opt=d_tx.init(d_params),
        step=jnp.zeros((), jnp.int32),
    )
