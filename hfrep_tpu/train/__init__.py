
from __future__ import annotations
from hfrep_tpu.train.states import GanState, init_gan_state  # noqa: F401
from hfrep_tpu.train.steps import make_train_step, make_multi_step  # noqa: F401
from hfrep_tpu.train.trainer import GanTrainer  # noqa: F401
