"""Structured per-epoch metric logging (JSONL + reference-format echo).

The reference's observability is ``print`` statements in the epoch loop
(``GAN/MTSS_WGAN_GP.py:284``) — including the WGAN quirk of printing
``1 − d_loss`` (``GAN/WGAN.py:208``) while WGAN-GP prints raw losses
(SURVEY §5.5).  Here metrics stream to JSONL with a console formatter
that reproduces the reference's exact print lines for eyeball
comparison, and every ``log()`` additionally forwards into the active
obs event stream (gauge metrics named ``train/<key>``) when telemetry
is enabled — one logging call site, two sinks, zero cost when obs is
off.

History: born as ``hfrep_tpu/utils/logging.py`` in PR 2, reduced to a
shim when the obs layer landed, moved HERE when the wall-clock ledger
(:mod:`hfrep_tpu.obs.timeline`) retired the shim tier — the epoch echo
is part of the observability surface, so it lives with it.  Its
companion shim ``utils.profiling.StepTimer`` is gone outright:
:class:`hfrep_tpu.obs.timeline.BlockTimer` is the one block-boundary
timing surface.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import IO, Mapping, Optional

import numpy as np

from hfrep_tpu.obs import get_obs


def _to_py(v):
    if isinstance(v, (np.ndarray, np.generic)):
        return np.asarray(v).item() if np.ndim(v) == 0 else np.asarray(v).tolist()
    try:
        import jax
        if isinstance(v, jax.Array):
            return _to_py(np.asarray(v))
    except ImportError:  # pragma: no cover
        pass
    return v


class MetricLogger:
    def __init__(self, path: Optional[str] = None, echo: bool = False,
                 echo_style: Optional[str] = None):
        """``echo_style`` in {None, "gan", "wgan", "wgan_gp"} reproduces
        the reference's console format for that family."""
        self.path = Path(path) if path else None
        self._fh: Optional[IO] = None
        if self.path:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a")
        self.echo = echo
        self.echo_style = echo_style
        self._t0 = time.perf_counter()

    def log(self, step: int, metrics: Mapping[str, object]) -> None:
        rec = {"step": int(step), "t": time.perf_counter() - self._t0}
        rec.update({k: _to_py(v) for k, v in metrics.items()})
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
        obs = get_obs()
        if obs.enabled:
            for k, v in rec.items():
                if k not in ("step", "t") and isinstance(v, (int, float)):
                    obs.gauge(f"train/{k}").set(v, step=int(step))
        if self.echo:
            print(self.format_line(step, rec))

    def format_line(self, step: int, m: Mapping) -> str:
        d, g = m.get("d_loss", float("nan")), m.get("g_loss", float("nan"))
        if self.echo_style == "gan":      # GAN/GAN.py:201
            return "%d [D loss: %f, acc.: %.2f%%] [G loss: %f]" % (step, d, 100 * m.get("d_acc", 0.0), g)
        if self.echo_style == "wgan":     # GAN/WGAN.py:208 prints 1 - loss
            return "%d [D loss: %f] [G loss: %f]" % (step, 1 - d, 1 - g)
        if self.echo_style == "wgan_gp":  # GAN/MTSS_WGAN_GP.py:284
            return "%d [D loss: %f] [G loss: %f]" % (step, d, g)
        return f"{step} " + " ".join(f"{k}={v}" for k, v in m.items() if k not in ("step", "t"))

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()

    def close(self) -> None:
        """Idempotent — a sweep's error path may close an already-closed
        logger (and ``__exit__`` always will after an explicit close)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # file handles must not leak when a sweep raises mid-run
        self.close()
