"""``python -m hfrep_tpu.obs`` — the obs CLI: report / gate / ingest.

Input is what the telemetry layer writes: ``run.json`` (manifest) and
``events.jsonl`` (span / metric / memory / event stream).  The headline
numbers mirror BASELINE.json's vocabulary so bench trajectories become
machine-diffable:

* ``steps/sec`` — steady-state rate from ``block`` spans (warmup spans,
  which carry XLA compile time, excluded whenever steady ones exist);
* ``p50/p95 step time`` — steps-weighted percentiles of per-epoch time
  across block spans;
* ``MFU`` — recomputed from the manifest's model shape via
  :mod:`hfrep_tpu.obs.flops` (falls back to an ``mfu`` gauge if the
  manifest lacks a config);
* ``memory high-water`` — max over ``memory`` events;
* compile accounting — backend compiles and total compile seconds.

Diff mode takes two run dirs and prints both columns plus the ratio —
``report A B`` answers "did this PR move steps/sec or memory?" without
eyeballing two JSONL files.  Everything here is stdlib-only (no jax
import), so the CLI is instant and runs in tier-1 via ``--self-test``.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from hfrep_tpu.obs import EVENT_TYPES, SCHEMA_VERSION

EVENTS_NAME = "events.jsonl"

#: per-type required fields, beyond the common ``v``/``t``/``type``
_REQUIRED_FIELDS = {
    "span": ("name", "dur", "depth"),
    "metric": ("kind", "name", "value"),
    "memory": ("high_water",),
    "event": ("name",),
}


class SchemaError(ValueError):
    """An event line failed schema validation."""


def parse_event(line: str, lineno: int = 0) -> Optional[dict]:
    """Parse + validate one JSONL line; blank lines return None."""
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except json.JSONDecodeError as e:
        raise SchemaError(f"line {lineno}: not JSON ({e})") from e
    if not isinstance(rec, dict):
        raise SchemaError(f"line {lineno}: event must be an object")
    if rec.get("v") != SCHEMA_VERSION:
        raise SchemaError(f"line {lineno}: schema version {rec.get('v')!r}, "
                          f"expected {SCHEMA_VERSION}")
    etype = rec.get("type")
    if etype not in EVENT_TYPES:
        raise SchemaError(f"line {lineno}: unknown event type {etype!r}")
    if not isinstance(rec.get("t"), (int, float)):
        raise SchemaError(f"line {lineno}: missing/invalid timestamp 't'")
    for field in _REQUIRED_FIELDS[etype]:
        if field not in rec:
            raise SchemaError(
                f"line {lineno}: {etype} event missing {field!r}")
    return rec


def load_jsonl(path, parse_line, strict: bool = False,
               torn_hint: str = "writer was likely killed mid-write",
               ) -> List[dict]:
    """The ONE torn-tail-tolerant JSONL loader (events AND the history
    index share it, so the tail policy cannot diverge between them): a
    final line missing its newline that fails ``parse_line`` is dropped
    with a warning — appenders buffer, so a killed writer tears exactly
    there and those files must stay readable.  Anything else — garbage
    mid-file, schema drift on a complete line — still raises
    :class:`SchemaError`; ``strict=True`` raises for the torn tail too
    (the self-tests use it: committed fixtures must be whole)."""
    path = Path(path)
    records = []
    with open(path) as fh:
        lines = fh.readlines()
    for i, line in enumerate(lines, 1):
        try:
            rec = parse_line(line, i)
        except SchemaError:
            if not strict and i == len(lines) and not line.endswith("\n"):
                print(f"warning: {path}: dropped torn final line {i} "
                      f"({torn_hint})", file=sys.stderr)
                break
            raise
        if rec is not None:
            records.append(rec)
    return records


def load_events(run_dir, strict: bool = False) -> List[dict]:
    """Parse + validate this run's event records (torn-tail policy:
    :func:`load_jsonl`).  On a compacted run dir the evidence records
    that ``obs compact`` pinned verbatim (``rollup/pinned-<n>.jsonl``)
    are replayed FIRST — they predate everything in the live stream by
    construction — then any rotated-but-not-yet-compacted chunks
    (``rollup/chunk-<n>.jsonl``: earlier bytes of the SAME stream the
    writer rotated aside mid-run), then the live tail, so readers see
    the same record sequence a raw, never-rotated stream would have
    given them.  High-volume records that compaction folded to
    aggregates are NOT here; ``summarize`` re-seeds their contribution
    from ``rollup/compact.json``."""
    records: List[dict] = []
    # lazy: rollup imports this module for the shared stream discipline
    from hfrep_tpu.obs import rollup as _rollup
    for pf in _rollup.pinned_files(run_dir):
        records.extend(load_jsonl(pf, parse_event, strict=strict,
                                  torn_hint="compactor was likely killed "
                                            "mid-publish"))
    for cf in _rollup.chunk_files(run_dir):
        records.extend(load_jsonl(cf, parse_event, strict=strict,
                                  torn_hint="writer was likely killed "
                                            "mid-rotation"))
    records.extend(load_jsonl(Path(run_dir) / EVENTS_NAME, parse_event,
                              strict=strict,
                              torn_hint="run was likely killed mid-write"))
    return records


# ------------------------------------------------------ trace collection
#: event names that terminate a trace — the zero-orphan contract
#: (``bench_serve --self-test``) asserts every submitted trace reaches
#: one of these
TERMINAL_TRACE_EVENTS = ("serve_complete", "serve_shed",
                         "serve_deadline_miss", "serve_degraded",
                         "serve_fault", "result_publish")


#: a live or rotated event stream — and nothing else: the crash bundle's
#: ``events_tail.jsonl`` is a COPY of stream tails, and matching it
#: would return every pre-crash hop twice on exactly the crashed run
#: dirs the flight recorder targets
_STREAM_NAME_RE = re.compile(r"^events(-\d+)?\.jsonl$")


def is_stream_file(path: Path) -> bool:
    return bool(_STREAM_NAME_RE.match(path.name))


def iter_event_files(run_dirs) -> List[Path]:
    """Every event stream under the given run dirs, recursively —
    rotated streams (``events-<n>.jsonl``) included, because a restarted
    member's pre-kill history is exactly what a cross-restart trace
    reconstruction needs; crash bundles' ``events_tail.jsonl`` copies
    excluded (they would double every pre-crash record)."""
    seen, out = set(), []
    for d in run_dirs:
        d = Path(d)
        files = ([d] if d.is_file()
                 else sorted(f for f in d.rglob("events*.jsonl")
                             if is_stream_file(f)))
        for f in files:
            if f not in seen:
                seen.add(f)
                out.append(f)
    return out


def _stream_rank(path: Path):
    """Ordering of streams within one run dir: rotated (earlier-run)
    streams sort before the live ``events.jsonl``, in rotation order."""
    name = path.name
    if name == EVENTS_NAME:
        return (1, 0)
    try:
        return (0, int(name[len("events-"):-len(".jsonl")]))
    except ValueError:
        return (0, 0)


def trace_index(run_dirs, trace_ids=None) -> Dict[str, List[dict]]:
    """Parse every stream under ``run_dirs`` ONCE and bucket the records
    by trace ID — the bulk form behind zero-orphan checks (calling
    :func:`trace_events` per ID would re-read and re-parse the whole
    run dir per trace).  ``trace_ids=None`` indexes every ID seen.

    Records match by the ``trace`` attr or by membership in a
    batch-level ``traces`` list, and come back annotated with ``_dir``/
    ``_file``/``_rotated``/``_abs`` (absolute unix time via the stream
    dir's manifest; None for rotated streams whose manifest the restart
    overwrote) and sorted into reconstruction order."""
    wanted = None if trace_ids is None else set(trace_ids)
    out: Dict[str, List[dict]] = ({} if wanted is None
                                  else {t: [] for t in wanted})
    for f in iter_event_files(run_dirs):
        try:
            recs = load_jsonl(f, parse_event)
        except (OSError, SchemaError):
            continue
        if f.name == EVENTS_NAME:
            # a compacted dir's pinned evidence records — and any
            # rotated-but-uncompacted chunks — belonged to THIS live
            # stream before rotation: replay them ahead of the live
            # tail under the live stream's own identity, so trace
            # reconstructions stay byte-equal to the raw-dir result
            from hfrep_tpu.obs import rollup as _rollup
            prior_recs: List[dict] = []
            for pf in (_rollup.pinned_files(f.parent)
                       + _rollup.chunk_files(f.parent)):
                try:
                    prior_recs.extend(load_jsonl(pf, parse_event))
                except (OSError, SchemaError):
                    continue
            recs = prior_recs + recs
        base = None
        try:
            base = json.loads(
                (f.parent / "run.json").read_text()).get("created_unix")
        except (OSError, json.JSONDecodeError):
            pass
        rotated = f.name != EVENTS_NAME
        for rec in recs:
            ids = []
            if isinstance(rec.get("trace"), str):
                ids.append(rec["trace"])
            traces = rec.get("traces")
            if isinstance(traces, list):
                ids.extend(t for t in traces if isinstance(t, str))
            hits = {i for i in ids if wanted is None or i in wanted}
            if not hits:
                continue
            r = dict(rec)
            r["_dir"] = str(f.parent)
            r["_file"] = str(f)
            r["_rotated"] = rotated
            r["_dir_base"] = base
            r["_abs"] = ((base + float(rec["t"]))
                         if base is not None and not rotated else None)
            for i in hits:
                out.setdefault(i, []).append(r)
    for recs in out.values():
        recs.sort(key=_trace_sort_key)
    return out


def trace_events(run_dirs, trace_id: str) -> List[dict]:
    """One trace's records in reconstruction order (see
    :func:`trace_index`)."""
    return trace_index(run_dirs, [trace_id]).get(trace_id, [])


def _trace_sort_key(r: dict):
    """Reconstruction order: absolute time where the stream has a
    manifest base; rotated streams (whose manifest the restart
    overwrote) anchor just BEFORE their dir's live stream — their events
    happened before the restart by definition; streams with no manifest
    at all sort last, by dir."""
    if r["_abs"] is not None:
        return (0, r["_abs"], r["_dir"],
                _stream_rank(Path(r["_file"])), float(r["t"]))
    if r["_dir_base"] is not None:          # rotated, base known
        return (0, r["_dir_base"] - 1e-3, r["_dir"],
                _stream_rank(Path(r["_file"])), float(r["t"]))
    return (1, 0.0, r["_dir"], _stream_rank(Path(r["_file"])),
            float(r["t"]))


def has_terminal(records: List[dict]) -> bool:
    return any(r["type"] == "event" and r.get("name") in
               TERMINAL_TRACE_EVENTS for r in records)


def render_trace(trace_id: str, records: List[dict], root=None) -> str:
    """The cross-process critical path, one line per hop with per-hop
    durations (absolute-clock deltas where both ends have a manifest
    base; same-stream ``t`` deltas otherwise; ``?`` across a restart
    whose rotated stream lost its manifest)."""
    if not records:
        return f"trace {trace_id}: no matching events"
    root = Path(root) if root is not None else None
    streams = {r["_file"] for r in records}
    lines = [f"trace {trace_id} — {len(records)} event(s) across "
             f"{len(streams)} stream(s)"]
    prev = None
    for r in records:
        d = Path(r["_dir"])
        label = str(d.relative_to(root)) if root and root in d.parents \
            else d.name
        if r["_rotated"]:
            label += f":{Path(r['_file']).name}"
        delta = ""
        if prev is not None:
            if r["_abs"] is not None and prev.get("_abs") is not None:
                delta = f"  (+{(r['_abs'] - prev['_abs']) * 1e3:.1f} ms)"
            elif r["_file"] == prev["_file"]:
                delta = f"  (+{(float(r['t']) - float(prev['t'])) * 1e3:.1f} ms)"
            else:
                delta = "  (+? across restart)"
        name = r.get("name", r["type"])
        attrs = {k: v for k, v in r.items()
                 if k not in ("v", "t", "type", "name", "trace", "traces")
                 and not k.startswith("_") and v is not None}
        detail = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        lines.append(f"  [{label:>24s}] t={float(r['t']):8.3f}s "
                     f"{r['type']:6s} {name:20s} {detail}{delta}")
        prev = r
    lines.append("terminal: " + ("yes" if has_terminal(records)
                                 else "NO (orphan trace)"))
    return "\n".join(lines)


def _weighted_percentile(pairs: List[Tuple[float, float]], q: float) -> float:
    """Nearest-rank percentile of (value, weight) pairs."""
    if not pairs:
        return float("nan")
    pairs = sorted(pairs)
    total = sum(w for _, w in pairs)
    if total <= 0:
        return float("nan")
    acc = 0.0
    for v, w in pairs:
        acc += w
        if acc >= q * total:
            return v
    return pairs[-1][0]


def summarize(run_dir, events: Optional[List[dict]] = None) -> dict:
    """One run directory -> headline summary dict (all JSON-safe).
    ``events``: the already-parsed stream, when the caller just loaded
    it (``obs explain`` parses every cohort run once for evidence —
    re-reading the same JSONL here would double the diagnosis's I/O)."""
    run_dir = Path(run_dir)
    try:
        from hfrep_tpu.obs.manifest import read_manifest
        manifest = read_manifest(run_dir)
    except (OSError, json.JSONDecodeError):
        manifest = {}
    if events is None:
        events = load_events(run_dir)

    # on a compacted run dir, pre-seed the aggregate contribution of the
    # records compaction folded away (metric samples, plain spans).
    # Dict insertion order is deliberate: the seed preserves the raw
    # stream's first-seen order for every name it holds, and everything
    # seen only in the live stream appends after — so gauge/counter/
    # count ordering matches a raw replay exactly.
    from hfrep_tpu.obs import rollup as _rollup
    seed = _rollup.summary_seed(run_dir)

    counts: Dict[str, int] = {}
    blocks: List[dict] = []
    gauges: Dict[str, float] = {}
    counters: Dict[str, float] = {}
    high_water = 0
    compile_spans = 0.0
    seed_events = 0
    if seed:
        for etype in seed.get("type_order") or []:
            counts[etype] = seed["counts"].get(etype, 0)
        gauges.update(seed["gauges"])
        counters.update(seed["counters"])
        seed_events = int(seed["n_events"])
    for rec in events:
        counts[rec["type"]] = counts.get(rec["type"], 0) + 1
        if rec["type"] == "span":
            if rec["name"] == "block" and rec.get("steps"):
                blocks.append(rec)
            elif str(rec["name"]).startswith("compile:"):
                compile_spans += float(rec["dur"])
        elif rec["type"] == "metric":
            if rec["kind"] == "gauge":
                gauges[rec["name"]] = rec["value"]
            elif rec["kind"] == "counter":
                counters[rec["name"]] = rec["value"]
        elif rec["type"] == "memory":
            high_water = max(high_water, int(rec.get("high_water") or 0))

    steady = [b for b in blocks if not b.get("warmup")]
    used = steady or blocks
    steps = sum(float(b["steps"]) for b in used)
    secs = sum(float(b["dur"]) for b in used)
    steps_per_sec = steps / secs if secs > 0 else float("nan")
    per_step = [(float(b["dur"]) / float(b["steps"]), float(b["steps"]))
                for b in used if float(b["steps"]) > 0]
    p50 = _weighted_percentile(per_step, 0.50)
    p95 = _weighted_percentile(per_step, 0.95)

    mfu_val = float("nan")
    model = (manifest.get("config") or {}).get("model") or {}
    train = (manifest.get("config") or {}).get("train") or {}
    if (model.get("family") == "mtss_wgan_gp" and model.get("window")
            and model.get("features")):
        # the analytic FLOPs model is flagship-only (trainer.py gates its
        # mfu gauge the same way): other families' epoch structure differs,
        # so recomputing would print a confidently wrong number
        from hfrep_tpu.obs import flops
        mfu_val = flops.mfu(steps_per_sec, int(model["window"]),
                            int(model["features"]),
                            int(model.get("hidden") or flops.H),
                            int(train.get("batch_size") or flops.B))
    elif isinstance(gauges.get("mfu"), (int, float)):
        mfu_val = float(gauges["mfu"])

    return {
        "run_dir": str(run_dir),
        "run_id": manifest.get("run_id") or run_dir.name,
        "git_sha": (manifest.get("git") or {}).get("sha"),
        "backend": (manifest.get("devices") or {}).get("backend"),
        "n_events": len(events) + seed_events,
        "event_counts": counts,
        "blocks": {"n": len(blocks), "steady": len(steady),
                   "warmup": len(blocks) - len(steady)},
        "steps": steps,
        "steps_per_sec": steps_per_sec,
        "step_time_p50_s": p50,
        "step_time_p95_s": p95,
        "mfu": mfu_val,
        "memory_high_water_bytes": high_water,
        "backend_compiles": counters.get("backend_compiles"),
        "compile_secs": (gauges.get("backend_compile_secs_total")
                         or compile_spans or None),
        "gauges": gauges,
        "counters": counters,
    }


# ------------------------------------------------------------- rendering
def _fmt(v, unit="") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        if unit == "%":
            return f"{v * 100:.2f}%"
        if unit == "s":
            return f"{v * 1e3:.3f} ms" if v < 1 else f"{v:.3f} s"
        if unit == "B":
            return _fmt_bytes(v)
        return f"{v:.2f}"
    if unit == "B":
        return _fmt_bytes(v)
    return str(v)


def _fmt_bytes(v) -> str:
    v = float(v)
    for suffix in ("B", "KiB", "MiB", "GiB"):
        if v < 1024 or suffix == "GiB":
            return f"{v:.1f} {suffix}" if suffix != "B" else f"{int(v)} B"
        v /= 1024
    return f"{v:.1f} GiB"


_ROWS = (
    ("events", "n_events", ""),
    ("steady blocks", None, ""),
    ("steps", "steps", ""),
    ("steps/sec", "steps_per_sec", ""),
    ("p50 step time", "step_time_p50_s", "s"),
    ("p95 step time", "step_time_p95_s", "s"),
    ("MFU (bf16 peak)", "mfu", "%"),
    ("memory high-water", "memory_high_water_bytes", "B"),
    ("backend compiles", "backend_compiles", ""),
    ("compile secs", "compile_secs", ""),
)


def _row_value(s: dict, key: Optional[str]):
    if key is None:
        return f"{s['blocks']['steady']}/{s['blocks']['n']}"
    return s.get(key)


def render(s: dict) -> str:
    lines = [f"run {s['run_id']}  (backend={s['backend'] or '?'}, "
             f"git={str(s['git_sha'])[:10]})"]
    for label, key, unit in _ROWS:
        v = _row_value(s, key)
        lines.append(f"  {label:18s} {v if key is None else _fmt(v, unit)}")
    return "\n".join(lines)


def render_diff(a: dict, b: dict) -> str:
    lines = [f"{'':20s} {a['run_id'][:22]:>22s} {b['run_id'][:22]:>22s} "
             f"{'ratio':>8s}"]
    for label, key, unit in _ROWS:
        va, vb = _row_value(a, key), _row_value(b, key)
        ratio = ""
        if (key is not None and isinstance(va, (int, float))
                and isinstance(vb, (int, float)) and not isinstance(va, bool)):
            fa, fb = float(va), float(vb)
            if fa and not math.isnan(fa) and not math.isnan(fb):
                ratio = f"{fb / fa:7.2f}x"
        sa = str(va) if key is None else _fmt(va, unit)
        sb = str(vb) if key is None else _fmt(vb, unit)
        lines.append(f"{label:20s} {sa:>22s} {sb:>22s} {ratio:>8s}")
    return "\n".join(lines)


# -------------------------------------------------------------- self-test
def fixture_dir() -> Path:
    """The committed fixture run directory the tier-1 gate parses."""
    return Path(__file__).resolve().parent / "_fixture"


def history_fixture_dir() -> Path:
    """The committed history fixture: ≥3 clean run dirs + one multi-host
    pair + one seeded-regression run + the pre-built ``history.jsonl``
    index over the clean runs (tier-1's perf-regression tripwire)."""
    return fixture_dir() / "history"


def self_test() -> int:
    """Exercise the event-schema parser + summary on the fixture run.

    Returns 0 on success; prints and returns 1 on any mismatch — wired
    into ``tools/check.sh`` so a schema drift (writer and parser
    disagreeing) fails tier-1 before it corrupts a real run's telemetry.
    """
    from hfrep_tpu.obs.manifest import REQUIRED_KEYS, read_manifest
    fx = fixture_dir()
    try:
        manifest = read_manifest(fx)
        missing = [k for k in REQUIRED_KEYS if k not in manifest]
        if missing:
            raise SchemaError(f"fixture manifest missing keys: {missing}")
        events = load_events(fx, strict=True)   # validates every line
        if not events:
            raise SchemaError("fixture events.jsonl is empty")
        present = {e["type"] for e in events}
        need = {"span", "metric", "memory"}
        if not need <= present:
            raise SchemaError(f"fixture lacks event types {need - present}")
        s = summarize(fx)
        for key in ("steps_per_sec", "step_time_p50_s", "step_time_p95_s",
                    "mfu"):
            v = s[key]
            if not isinstance(v, float) or math.isnan(v):
                raise SchemaError(f"fixture summary {key} = {v!r}")
        if not s["memory_high_water_bytes"] > 0:
            raise SchemaError("fixture summary has no memory high-water")
    except (OSError, json.JSONDecodeError, SchemaError, KeyError) as e:
        print(f"obs self-test FAILED: {e}", file=sys.stderr)
        return 1
    print(f"obs self-test OK ({s['n_events']} events, "
          f"{s['steps_per_sec']:.1f} steps/s, mfu {s['mfu'] * 100:.2f}%)")
    return 0


def gate_self_test() -> int:
    """Exercise the full history/regression loop on the committed
    fixture: ingest, multi-host merge, baseline math, verdict shape and
    the pass/fail decision — strict mode throughout, with ONE pure-JSON
    result document on stdout (diagnostics go to stderr) so
    ``tools/check.sh --format json`` consumers stay machine-parseable.

    Wired into tier-1: if the writer, the store or the engine drift
    apart, CI fails before a real run's history is corrupted.
    """
    import tempfile

    from hfrep_tpu.obs import history as hist_mod
    from hfrep_tpu.obs import regress

    fx = history_fixture_dir()
    try:
        records = hist_mod.load_history(fx / "history.jsonl", strict=True)
        if len(records) < 3:
            raise SchemaError(f"fixture history holds {len(records)} "
                              "records, need >= 3 for baseline math")

        # the clean (un-indexed) run gates PASS against the committed
        # index, with the baseline actually ENFORCED (n >= min_runs —
        # an insufficient-history pass would not prove the math)
        clean = hist_mod.summarize_run(fx / "run_d")
        v_clean = regress.check_run(clean, records)
        if not v_clean["ok"]:
            raise SchemaError(
                f"clean fixture run flagged: {v_clean['regressions']}")
        if not any(c["status"] == "ok" and c["metric"] == "steps_per_sec"
                   for c in v_clean["checks"]):
            raise SchemaError("clean run's steps_per_sec was not enforced "
                              "(insufficient history in the fixture index?)")

        # the seeded regression gates FAIL, and the verdict names the
        # metric, baseline, observed value and threshold (ISSUE 3
        # acceptance shape)
        bad = hist_mod.summarize_run(fx / "regressed")
        v_bad = regress.check_run(bad, records)
        if v_bad["ok"] or "steps_per_sec" not in v_bad["regressions"]:
            raise SchemaError("seeded regression not flagged on "
                              f"steps_per_sec: {v_bad['regressions']}")
        (spc,) = [c for c in v_bad["checks"]
                  if c["metric"] == "steps_per_sec"]
        for field in ("metric", "baseline", "observed", "threshold"):
            if spc.get(field) is None:
                raise SchemaError(f"verdict check missing {field!r}")
        if not spc["observed"] < spc["baseline"] - spc["threshold"]:
            raise SchemaError("verdict numbers do not justify the flag")

        # cross-host merge: conservative folds over the committed pair
        merged = hist_mod.merge_run_dirs(fx / "multihost")
        per = merged["per_host"]
        if merged["hosts"] != 2 or len(per) != 2:
            raise SchemaError(f"multihost merge saw {merged['hosts']} hosts")
        rates = [h["steps_per_sec"] for h in per.values()]
        if merged["steps_per_sec"] != min(rates):
            raise SchemaError("merged steps/sec is not the min over hosts")
        if merged["memory_high_water_bytes"] != max(
                h["memory_high_water_bytes"] for h in per.values()):
            raise SchemaError("merged memory high-water is not the max")
        if merged["backend_compiles"] != sum(
                h["backend_compiles"] for h in per.values()):
            raise SchemaError("merged compile count is not the sum")

        # ingest round trip + idempotency into a scratch index
        with tempfile.TemporaryDirectory() as td:
            scratch = Path(td) / "history.jsonl"
            first = hist_mod.ingest(fx / "run_c", scratch)
            again = hist_mod.ingest(fx / "run_c", scratch)
            mh = hist_mod.ingest_multihost(fx / "multihost", scratch)
            if not first["ingested"] or again["ingested"]:
                raise SchemaError("ingest is not idempotent on "
                                  "(run_id, created_unix)")
            if not mh["ingested"] or mh["hosts"] != 2:
                raise SchemaError("multihost ingest did not merge 2 hosts")
            back = hist_mod.load_history(scratch, strict=True)
            if len(back) != 2:
                raise SchemaError(f"scratch index holds {len(back)} records,"
                                  " expected 2")
    except (OSError, json.JSONDecodeError, SchemaError, KeyError,
            ValueError) as e:
        print(f"obs gate self-test FAILED: {e}", file=sys.stderr)
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print("obs gate self-test OK", file=sys.stderr)
    print(json.dumps({
        "ok": True,
        "history_records": len(records),
        "clean_run": {"run_id": v_clean["run_id"], "ok": True},
        "regressed_run": {"run_id": v_bad["run_id"], "ok": False,
                          "regressions": v_bad["regressions"],
                          "steps_per_sec": {
                              "baseline": spc["baseline"],
                              "observed": spc["observed"],
                              "threshold": spc["threshold"]}},
        "multihost": {"hosts": merged["hosts"],
                      "steps_per_sec": merged["steps_per_sec"]},
    }))
    return 0


# -------------------------------------------------------------------- CLI
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m hfrep_tpu.obs",
        description="summarize / diff / gate telemetry run directories")
    sub = p.add_subparsers(dest="command", required=True)

    r = sub.add_parser("report", help="summarize one run dir or diff two")
    r.add_argument("run_dirs", nargs="*", help="1 run dir (summary) or "
                                               "2 (diff: second vs first)")
    r.add_argument("--format", choices=("human", "json"), default="human")
    r.add_argument("--merge", action="store_true",
                   help="treat each RUN_DIR as a multi-host launch parent "
                        "(proc0/, proc1/, ...) and summarize the folded "
                        "logical run (history.merge_run_dirs)")
    r.add_argument("--trace", metavar="ID", default=None,
                   help="reconstruct one request/item's cross-process "
                        "critical path: every event carrying this trace "
                        "ID across ALL events*.jsonl streams under the "
                        "given run dir(s), rotated streams included, "
                        "with per-hop durations")
    r.add_argument("--crash", action="store_true",
                   help="read the run dir's crash-forensics bundle "
                        "(crash_<run_id>/): exception, traceback tail, "
                        "last events")
    r.add_argument("--self-test", action="store_true",
                   help="validate the committed fixture run dir (CI gate)")

    g = sub.add_parser(
        "gate", help="perf-regression gate: one run vs the run history")
    g.add_argument("run_dir", nargs="?",
                   help="run dir to gate (omit with --self-test)")
    g.add_argument("--history", default=None,
                   help="history.jsonl index (default: $HFREP_HISTORY)")
    g.add_argument("--format", choices=("human", "json"), default="human")
    g.add_argument("--merge", action="store_true",
                   help="RUN_DIR is a multi-host parent; gate the folded run")
    g.add_argument("--ingest", action="store_true",
                   help="append the run to the history AFTER a passing "
                        "gate (a regressed run must not become its own "
                        "baseline)")
    g.add_argument("--min-runs", type=int, default=None, metavar="N",
                   help="comparable runs required before enforcing "
                        "(default 3; fewer passes as insufficient-history)")
    g.add_argument("--window", type=int, default=None, metavar="N",
                   help="rolling baseline window (last N comparable runs)")
    g.add_argument("--threshold", action="append", default=None,
                   metavar="METRIC=REL_TOL",
                   help="set a metric's EXACT relative tolerance (replaces "
                        "the adaptive MAD term), e.g. steps_per_sec=0.08 "
                        "(repeatable)")
    g.add_argument("--self-test", action="store_true",
                   help="exercise ingest/merge/baseline/verdict on the "
                        "committed history fixture (CI gate; pure-JSON "
                        "stdout)")
    g.add_argument("--slo", default=None, metavar="FLEET_ROOT",
                   help="also evaluate the declarative SLO burn rates "
                        "over this fleet root and fail the gate on any "
                        "breach (with no RUN_DIR: pure SLO gating, no "
                        "per-run regression check)")
    g.add_argument("--slos", default=None, metavar="FILE",
                   help="with --slo: objectives JSON (default: "
                        "<root>/slo.json if present, else built-ins)")
    g.add_argument("--explain", action="store_true",
                   help="on a failing gate, diff the offending run "
                        "against the comparable history runs still on "
                        "disk (program fingerprints, compile counts, "
                        "cost-analysis flops, span/attrib deltas) and "
                        "append a ranked diagnosis to the verdict")

    x = sub.add_parser(
        "explain", help="ranked regression diagnosis: diff the LAST run "
                        "dir against the earlier one(s) as baseline "
                        "cohort — program fingerprints, compile counts, "
                        "cost-analysis flops, dispatch-vs-compute and "
                        "span deltas, worst first")
    x.add_argument("run_dirs", nargs="*",
                   help="BASELINE [BASELINE...] TARGET (>= 2; the last "
                        "dir is the offending run; omit with "
                        "--self-test/--history)")
    x.add_argument("--format", choices=("human", "json"), default="human")
    x.add_argument("--top", type=int, default=10, metavar="N",
                   help="keep the N highest-scored findings (default 10)")
    x.add_argument("--history", default=None, metavar="PATH",
                   help="instead of diffing run dirs, report what the "
                        "history STORE alone can attribute: per-metric "
                        "series + an evidence inventory (compile "
                        "counters / memory / resolvable run dirs per "
                        "record)")
    x.add_argument("--self-test", action="store_true",
                   help="exercise the diagnosis loop on the committed "
                        "planted-regression fixture (CI gate; pure-JSON "
                        "stdout)")

    pr = sub.add_parser(
        "profile", help="digest a run dir's captured profiler traces "
                        "(trace_capture artifacts under <run_dir>/traces) "
                        "into per-op / per-region device time tables; "
                        "typed skip when the run captured none")
    pr.add_argument("run_dir")
    pr.add_argument("--format", choices=("human", "json"), default="human")
    pr.add_argument("--top", type=int, default=20, metavar="N",
                    help="ops per capture in the table (default 20)")

    i = sub.add_parser(
        "ingest", help="append a run dir to a history.jsonl index")
    i.add_argument("run_dir")
    i.add_argument("--history", required=True)
    i.add_argument("--merge", action="store_true",
                   help="RUN_DIR is a multi-host parent; ingest the "
                        "folded logical run")

    t = sub.add_parser(
        "tail", help="live one-screen view of a running run dir "
                     "(steps/sec, health/* gauges, queue depth, shed "
                     "rate, breaker state) following the torn-tail-"
                     "tolerant JSONL streams")
    t.add_argument("run_dirs", nargs="+")
    t.add_argument("--interval", type=float, default=1.0,
                   help="refresh period in seconds (default 1.0)")
    t.add_argument("--once", action="store_true",
                   help="render a single frame and exit (CI/scripting)")

    e = sub.add_parser(
        "export", help="Prometheus-exposition-format text snapshot of a "
                       "run dir's gauges/counters/histograms (for "
                       "external scrapers)")
    e.add_argument("run_dirs", nargs="+")
    e.add_argument("-o", "--out", default=None,
                   help="write to this file (atomic tmp+rename) instead "
                        "of stdout")
    e.add_argument("--fleet", action="store_true",
                   help="treat the single argument as a FLEET ROOT: "
                        "discover every run dir beneath it, fold each "
                        "through the durable rollup consumer and emit "
                        "ONE federated exposition — per-replica series "
                        "labeled {replica=...} plus hfrep_fleet_* "
                        "invariant gauges (ledger deficit, breakers, "
                        "restart storms)")
    e.add_argument("--watch", type=int, default=None, metavar="N",
                   help="with --fleet: keep re-ingesting and "
                        "republishing every --interval seconds for N "
                        "passes (advances the durable cursors)")
    e.add_argument("--interval", type=float, default=5.0,
                   help="with --fleet --watch: seconds between passes "
                        "(default 5.0)")

    s = sub.add_parser(
        "slo", help="declarative SLOs with multi-window burn-rate "
                    "alerts over a fleet root (p95 latency, shed rate, "
                    "error rate vs targets; breach = fast AND slow "
                    "windows both burning >= 1.0)")
    s.add_argument("root", nargs="?",
                   help="fleet root (omit with --self-test)")
    s.add_argument("--slos", default=None, metavar="FILE",
                   help="objectives JSON (default: <root>/slo.json if "
                        "present, else the built-in serve objectives)")
    s.add_argument("--fast-buckets", type=int, default=None, metavar="N",
                   help="fast burn window, in rollup buckets (default 5)")
    s.add_argument("--slow-buckets", type=int, default=None, metavar="N",
                   help="slow burn window, in rollup buckets (default 30)")
    s.add_argument("--bucket-secs", type=float, default=None,
                   help="rollup bucket width in seconds (default 60)")
    s.add_argument("--persist", action="store_true",
                   help="advance each replica's durable rollup cursors "
                        "(default: read-only fold)")
    s.add_argument("--format", choices=("human", "json"), default="human")
    s.add_argument("--self-test", action="store_true",
                   help="evaluate the committed two-replica fleet "
                        "fixture: the planted cross-replica silent drop "
                        "and burn-rate breach must be caught (CI gate; "
                        "pure-JSON stdout)")

    tl = sub.add_parser(
        "timeline", help="wall-clock ledger: fold a run's "
                         "timeline_window records into the whole-run "
                         "conservation ledger (Σ category ms == wall "
                         "ms), report per-category fractions + achieved "
                         "host/device overlap, and optionally "
                         "reconstruct a Chrome-trace/perfetto timeline "
                         "from the event stream alone (byte-identical "
                         "on a compacted run dir)")
    tl.add_argument("run_dir", nargs="?",
                    help="run dir to account (omit with --self-test)")
    tl.add_argument("-o", "--out", default=None, metavar="TRACE_JSON",
                    help="also write the perfetto trace-event JSON here "
                         "(atomic tmp+rename; open in ui.perfetto.dev "
                         "or chrome://tracing)")
    tl.add_argument("--format", choices=("human", "json"), default="human")
    tl.add_argument("--self-test", action="store_true",
                    help="accumulator conservation algebra + the "
                         "hand-computed fixture ledger + compaction "
                         "byte-identity + torn-tail degradation + the "
                         "obs_self_frac<1%% ceiling (CI gate; pure-JSON "
                         "stdout)")

    c = sub.add_parser(
        "compact", help="bounded retention for long soaks: rotate an "
                        "oversized live stream aside, fold rotated "
                        "chunks into rollup segments + a reader seed, "
                        "pin the evidence records verbatim, delete the "
                        "chunks — gate/explain/--trace verdicts stay "
                        "identical on the compacted dir")
    c.add_argument("run_dirs", nargs="+")
    c.add_argument("--rotate-bytes", type=int, default=None, metavar="N",
                   help="also rotate the live stream first when it "
                        "exceeds N bytes (caller must know no writer "
                        "holds it open; live processes rotate "
                        "themselves via HFREP_OBS_ROTATE_BYTES)")
    c.add_argument("--force-rotate", action="store_true",
                   help="rotate a non-empty live stream regardless of "
                        "size (offline runs only)")
    c.add_argument("--bucket-secs", type=float, default=None,
                   help="rollup bucket width in seconds (default 60)")
    c.add_argument("--format", choices=("human", "json"), default="human")

    sub.add_parser(
        "crash-drill",
        help="CI gate for the crash-forensics loop: run a real obs "
             "session through injected io_fail + nonfinite faults, "
             "assert the crash bundle lands complete and renders "
             "(tools/check.sh)")
    return p


def _parse_threshold_overrides(pairs):
    if not pairs:
        return None
    out = {}
    for pair in pairs:
        metric, _, tol = pair.partition("=")
        if not metric or not tol:
            raise ValueError(f"--threshold wants METRIC=REL_TOL, got {pair!r}")
        out[metric] = float(tol)
    return out


def _cmd_report(args) -> int:
    if args.self_test:
        return self_test()
    if args.crash:
        from hfrep_tpu.obs import crash
        if len(args.run_dirs) != 1:
            print("report --crash wants exactly one run dir (or bundle "
                  "dir)", file=sys.stderr)
            return 2
        bundle = crash.find_bundle(args.run_dirs[0])
        if bundle is None:
            print(f"no crash bundle under {args.run_dirs[0]}",
                  file=sys.stderr)
            return 1
        if args.format == "json":
            try:
                print(json.dumps(json.loads(
                    (bundle / "crash.json").read_text()), indent=2))
            except (OSError, json.JSONDecodeError) as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
        else:
            print(crash.render_bundle(bundle))
        return 0
    if args.trace:
        if not args.run_dirs:
            print("report --trace wants at least one run dir",
                  file=sys.stderr)
            return 2
        records = trace_events(args.run_dirs, args.trace)
        if args.format == "json":
            print(json.dumps({"trace": args.trace,
                              "terminal": has_terminal(records),
                              "events": records}, indent=2, default=str))
        else:
            print(render_trace(args.trace, records,
                               root=Path(args.run_dirs[0]).resolve()))
        return 0 if records else 1
    if not 1 <= len(args.run_dirs) <= 2:
        print("report wants 1 run dir (summary) or 2 (diff)", file=sys.stderr)
        return 2
    try:
        if args.merge:
            from hfrep_tpu.obs.history import merge_run_dirs
            summaries = [merge_run_dirs(d) for d in args.run_dirs]
        else:
            summaries = [summarize(d) for d in args.run_dirs]
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.format == "json":
        doc = summaries[0] if len(summaries) == 1 else {
            "base": summaries[0], "other": summaries[1]}
        print(json.dumps(doc, indent=2, default=str))
        return 0
    if len(summaries) == 1:
        print(render(summaries[0]))
    else:
        print(render_diff(summaries[0], summaries[1]))
    return 0


def _cmd_gate(args) -> int:
    import os

    from hfrep_tpu.obs import history as hist_mod
    from hfrep_tpu.obs import regress

    if args.self_test:
        return gate_self_test()

    slo_doc = None
    if args.slo:
        from hfrep_tpu.obs import slo as slo_mod
        try:
            slo_doc = slo_mod.evaluate_root(args.slo,
                                            slos_path=args.slos)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: --slo: {e}", file=sys.stderr)
            return 2
        if not args.run_dir:
            # pure SLO gating: no per-run regression half
            if args.format == "json":
                print(json.dumps(slo_doc, indent=2, default=str))
            else:
                print(slo_mod.render(slo_doc))
            ok = slo_doc["ok"] and slo_doc["fleet"]["ok"]
            print("slo gate: " + ("PASS" if ok else "FAIL"),
                  file=sys.stderr)
            return 0 if ok else 1

    if not args.run_dir:
        print("gate wants a run dir (or --self-test / --slo ROOT)",
              file=sys.stderr)
        return 2
    history_path = args.history or os.environ.get("HFREP_HISTORY")
    if not history_path:
        print("gate wants --history PATH (or $HFREP_HISTORY)",
              file=sys.stderr)
        return 2
    try:
        overrides = _parse_threshold_overrides(args.threshold)
        record = (hist_mod.merged_record(args.run_dir) if args.merge
                  else hist_mod.summarize_run(args.run_dir))
        records = hist_mod.load_history(history_path)
        kw = {"thresholds": overrides}
        if args.min_runs is not None:
            kw["min_runs"] = args.min_runs
        if args.window is not None:
            kw["window"] = args.window
        verdict = regress.check_run(record, records, **kw)
    except (OSError, SchemaError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    explain_doc = None
    if args.explain and not verdict["ok"]:
        # the gate's red exit becomes a diagnosis: diff against the
        # comparable history runs still on disk.  Best-effort — a
        # failed explanation must never change the gate's verdict.
        from hfrep_tpu.obs import explain as explain_mod
        try:
            explain_doc = explain_mod.explain_gate_failure(
                args.run_dir, record, records, history_path=history_path,
                window=args.window or regress.DEFAULT_WINDOW)
        except Exception as e:
            print(f"explain failed ({e}); verdict unaffected",
                  file=sys.stderr)
    extra = {}
    if explain_doc is not None:
        extra["explain"] = explain_doc
    if slo_doc is not None:
        extra["slo"] = slo_doc
    if args.format == "json":
        if extra:
            print(json.dumps(dict(verdict, **extra), indent=2,
                             default=str))
        else:
            print(regress.verdict_json(verdict))
    else:
        print(regress.render_verdict(verdict))
        if explain_doc is not None:
            from hfrep_tpu.obs import explain as explain_mod
            print(explain_mod.render_diagnosis(explain_doc))
        if slo_doc is not None:
            from hfrep_tpu.obs import slo as slo_mod
            print(slo_mod.render(slo_doc))
    if verdict["ok"] and args.ingest:
        try:
            ok = hist_mod.append_record(
                history_path, dict(record, ingested_unix=round(time.time(), 3)),
                records=records)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(("ingested into" if ok else "already indexed in")
              + f" {history_path}", file=sys.stderr)
    slo_ok = (slo_doc is None
              or (slo_doc["ok"] and slo_doc["fleet"]["ok"]))
    if not slo_ok:
        print("slo gate: FAIL (burn-rate breach or fleet invariant)",
              file=sys.stderr)
    return 0 if (verdict["ok"] and slo_ok) else 1


def _cmd_ingest(args) -> int:
    from hfrep_tpu.obs import history as hist_mod
    try:
        rec = (hist_mod.ingest_multihost(args.run_dir, args.history)
               if args.merge else hist_mod.ingest(args.run_dir, args.history))
    except (OSError, SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(rec, indent=2, default=str))
    return 0


def _cmd_explain(args) -> int:
    from hfrep_tpu.obs import explain as explain_mod
    if args.self_test:
        return explain_mod.self_test()
    if args.history:
        from hfrep_tpu.obs import history as hist_mod
        try:
            records = hist_mod.load_history(args.history)
        except (OSError, SchemaError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        doc = explain_mod.history_report(records)
        if args.format == "json":
            print(json.dumps(doc, indent=2, default=str))
        else:
            print(explain_mod.render_history_report(doc))
        return 0
    if len(args.run_dirs) < 2:
        print("explain wants BASELINE [BASELINE...] TARGET run dirs "
              "(or --history / --self-test)", file=sys.stderr)
        return 2
    doc = explain_mod.explain_runs(args.run_dirs[:-1], args.run_dirs[-1],
                                   top=args.top)
    if args.format == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(explain_mod.render_diagnosis(doc))
    return 0


def _cmd_profile(args) -> int:
    from hfrep_tpu.obs import attrib
    return attrib.profile_main(args.run_dir, top=args.top,
                               fmt=args.format)


def _cmd_tail(args) -> int:
    from hfrep_tpu.obs import tail
    return tail.tail_main(args.run_dirs, interval=args.interval,
                          once=args.once)


def _cmd_export(args) -> int:
    if args.fleet:
        from hfrep_tpu.obs import fleet
        if len(args.run_dirs) != 1:
            print("export --fleet wants exactly one fleet root",
                  file=sys.stderr)
            return 2
        return fleet.export_fleet_main(
            args.run_dirs[0], out=args.out,
            watch_iterations=args.watch, interval=args.interval,
            persist=args.watch is not None)
    from hfrep_tpu.obs import tail
    return tail.export_main(args.run_dirs, out=args.out)


def _cmd_slo(args) -> int:
    from hfrep_tpu.obs import slo as slo_mod
    if args.self_test:
        return slo_mod.self_test()
    if not args.root:
        print("slo wants a fleet root (or --self-test)", file=sys.stderr)
        return 2
    kw = {"slos_path": args.slos, "persist": args.persist}
    if args.fast_buckets is not None:
        kw["fast_buckets"] = args.fast_buckets
    if args.slow_buckets is not None:
        kw["slow_buckets"] = args.slow_buckets
    if args.bucket_secs is not None:
        kw["bucket_secs"] = args.bucket_secs
    try:
        doc = slo_mod.evaluate_root(args.root, **kw)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(slo_mod.render(doc))
        led = doc["fleet"]["ledger"]
        print(f"fleet: {doc['fleet']['replicas']} replica(s), ledger "
              f"{led['submitted']}→{led['terminal']} "
              f"(deficit {led['deficit']}), "
              f"{doc['fleet']['breakers']['open']} breaker(s) open, "
              f"{len(doc['fleet']['restarts']['storms'])} restart "
              f"storm(s)")
    return 0 if (doc["ok"] and doc["fleet"]["ok"]) else 1


def _cmd_compact(args) -> int:
    from hfrep_tpu.obs import rollup
    kw = {}
    if args.bucket_secs is not None:
        kw["bucket_secs"] = args.bucket_secs
    results = []
    rc = 0
    for d in args.run_dirs:
        try:
            res = rollup.compact(d, rotate_bytes=args.rotate_bytes,
                                 force_rotate=args.force_rotate, **kw)
        except OSError as e:
            print(f"error: {d}: {e}", file=sys.stderr)
            rc = 1
            continue
        res["run_dir"] = str(d)
        res["disk_bytes"] = rollup.disk_footprint(d)
        results.append(res)
    if args.format == "json":
        print(json.dumps(results, indent=2, default=str))
    else:
        for res in results:
            print(f"{res['run_dir']}: ingested {res['ingested']} "
                  f"record(s), compacted {len(res['compacted'])} "
                  f"chunk(s) ({res['chunks_total']} total, "
                  f"{res['records_compacted']} records), "
                  f"disk {res['disk_bytes']} B"
                  + (f", rotated {res['rotated']}" if res["rotated"]
                     else ""))
    return rc


def _cmd_timeline(args) -> int:
    from hfrep_tpu.obs import timeline
    if args.self_test:
        return timeline.self_test()
    if not args.run_dir:
        print("timeline wants a run dir (or --self-test)", file=sys.stderr)
        return 2
    return timeline.timeline_main(args.run_dir, out=args.out,
                                  fmt=args.format)


def _cmd_crash_drill(args) -> int:
    from hfrep_tpu.obs import crash
    return crash.drill()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return {"report": _cmd_report, "gate": _cmd_gate,
            "ingest": _cmd_ingest, "tail": _cmd_tail,
            "export": _cmd_export, "explain": _cmd_explain,
            "profile": _cmd_profile, "slo": _cmd_slo,
            "compact": _cmd_compact, "timeline": _cmd_timeline,
            "crash-drill": _cmd_crash_drill}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
