"""Perf microscope, write side: compiled-program fingerprints,
dispatch-vs-compute attribution, and xprof-trace digestion.

The sentinel (PR 3) says *whether* a run regressed and the flight
recorder (PR 12) says *what happened*; nothing in the repo could say
*why* — the BENCH_r01–r05 headline sat 5–7% under its own recorded
``vs_baseline`` for five rounds and nobody could tell a recompile from
a fusion change from dispatch overhead, because no run records what
programs it actually compiled or where its wall clock went.  This
module is the per-program cost/attribution layer (the third pillar of
profiling-in-production; cf. the xprof/roofline methodology in
PAPERS.md's scaling references):

* **program fingerprints** — at every compile boundary the repo owns
  (``instrument_step``-wrapped train steps, the AE engine's chunk
  program cache, ``serve/aot.py``'s AOT compiles, ``bench.py``'s timed
  programs), :func:`profile_jitted` / :func:`profile_stage` capture the
  lowered program text's sha256 digest plus ``cost_analysis()`` /
  ``memory_analysis()`` where the runtime carries them (graceful None
  otherwise — every jax access is gated through
  :mod:`hfrep_tpu.utils.jax_compat`), land them as ``program_profile``
  events and index them in ``run.json``'s ``programs`` section — a
  silent recompile or fusion change between two runs becomes a
  machine-diffable fact (:mod:`hfrep_tpu.obs.explain` consumes it);
* **dispatch-vs-compute attribution** — :func:`note_dispatch` /
  :func:`flush_window` split an instrumented drive's wall clock into
  host-dispatch time (the un-blocked jitted-call returns XLA's async
  dispatch hands back immediately) vs the residual the host spent
  blocked on device compute, measured ONLY at the block boundaries the
  drives already sync at (``BlockTimer.stop``, the AE engine's
  continue/stop scalar) — zero new syncs inside scans, no-op when obs
  is off, trajectories bit-identical (the PR-12 discipline; pinned by
  ``tests/test_obs_attrib.py``).  Surfaced as
  ``attrib/{dispatch_ms,compute_ms,dispatch_frac}`` gauges;
* **trace digestion** — :func:`profile_run` parses the
  ``trace_capture`` artifacts PR 3 lands under ``<run_dir>/traces``
  (perfetto trace-event JSON; best-effort, typed
  :class:`TraceUnavailable` when absent) into per-op / per-region time
  tables with interval-union busy time (nested parent ops — a ``while``
  spans its body — must not double-count), consolidating the parsing
  that ``tools/mfu_trace_probe.py`` grew privately.

Everything that *reads* (trace digestion) is stdlib-only; everything
that *captures* imports jax lazily and only when a sink is enabled.
"""

from __future__ import annotations

import glob
import gzip
import hashlib
import json
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from hfrep_tpu.obs import get_obs

#: the event-stream name program fingerprints land under (documented in
#: obs/README.md; hfrep_tpu/obs/explain.py and the manifest ``programs``
#: section are the two readers)
PROGRAM_EVENT = "program_profile"

#: cost_analysis keys normalized into the profile (the jax cost model's
#: names, spaces and all); everything else stays behind in the raw dict
_COST_KEYS = (("flops", "flops"),
              ("bytes accessed", "bytes_accessed"),
              ("transcendentals", "transcendentals"))


def fingerprint_text(text: Optional[str]) -> Optional[str]:
    """sha256 hex digest of a lowered/compiled program's text — the
    machine-diffable identity of "the same program"."""
    if not text:
        return None
    return hashlib.sha256(text.encode()).hexdigest()


def _profile_dict(name: str, stage, compiled=None) -> dict:
    """The JSON-safe profile of one compile boundary.  ``stage`` is the
    Lowered (or anything with ``as_text``/``cost_analysis``);
    ``compiled`` optionally adds the Compiled's ``memory_analysis``."""
    from hfrep_tpu.utils import jax_compat

    text = jax_compat.stage_hlo_text(stage)
    cost = jax_compat.stage_cost_analysis(stage)
    if cost is None and compiled is not None:
        cost = jax_compat.stage_cost_analysis(compiled)
    prof = {
        "name": str(name),
        "hlo_sha256": fingerprint_text(text),
        "hlo_bytes": len(text) if text else None,
        "cost": ({dst: cost.get(src) for src, dst in _COST_KEYS}
                 if cost else None),
        "memory": jax_compat.stage_memory_analysis(
            compiled if compiled is not None else stage),
    }
    return prof


def profile_stage(name: str, stage, compiled=None) -> Optional[dict]:
    """Fingerprint an already-lowered/compiled stage into the active
    run: one ``program_profile`` event + a ``run.json`` ``programs``
    entry.  No-op (None) when telemetry is off; never raises into the
    caller — a failed fingerprint must not cost the program it
    describes."""
    obs = get_obs()
    if not obs.enabled:
        return None
    try:
        prof = _profile_dict(name, stage, compiled)
        payload = dict(prof)
        # the boundary name rides as ``program`` — the event's own
        # ``name`` is the type tag ("program_profile") and must not be
        # overwritten by the profile's
        payload["program"] = payload.pop("name")
        obs.event("program_profile", **payload)
        from hfrep_tpu.obs import manifest
        manifest.add_program(obs.run_dir, prof)
        return prof
    except Exception:
        return None


def profile_jitted(fn, name: str, *args, **kwargs) -> Optional[dict]:
    """Fingerprint a jitted callable at a compile boundary by lowering
    it against the example operands (trace + lower only — no second XLA
    compile, no execution, donated buffers untouched).  No-op when
    telemetry is off or the callable/runtime cannot lower (a wrapped
    non-jit function, a non-jax operand): the boundary stays
    fingerprint-less, never broken."""
    obs = get_obs()
    if not obs.enabled:
        return None
    from hfrep_tpu.utils import jax_compat
    lowered = jax_compat.lower_jitted(fn, *args, **kwargs)
    if lowered is None:
        return None
    return profile_stage(name, lowered)


# ------------------------------------------- dispatch-vs-compute windows
class _Window:
    """The open attribution window: host-dispatch seconds accumulated
    per step name since the last boundary flush.  One process drives one
    step at a time, so a single module-level window (guarded for the
    serve layer's threads) is the whole story; per-name detail rides the
    gauge attrs."""

    def __init__(self):
        self.lock = threading.Lock()
        self.dispatch_s: Dict[str, float] = {}
        self.calls: Dict[str, int] = {}

    def note(self, name: str, dur_s: float) -> None:
        with self.lock:
            self.dispatch_s[name] = self.dispatch_s.get(name, 0.0) + dur_s
            self.calls[name] = self.calls.get(name, 0) + 1

    def take(self) -> Tuple[Dict[str, float], Dict[str, int]]:
        with self.lock:
            d, c = self.dispatch_s, self.calls
            self.dispatch_s, self.calls = {}, {}
            return d, c


_WINDOW = _Window()


def note_dispatch(name: str, dur_s: float) -> None:
    """Record one un-blocked jitted call's host-side duration (callers
    gate on ``obs.enabled`` at build/drive time, so the off path never
    reaches here).  Pure accumulation — no event, no sync."""
    _WINDOW.note(name, dur_s)


def reset_window() -> None:
    """Discard the open window (warmup blocks: their dispatch carries
    XLA compile time and would poison the first steady attribution)."""
    _WINDOW.take()


def window_calls() -> int:
    """How many dispatches the open window holds, without draining it —
    the probe callers use to decide whether the steps they just drove
    were already instrumented (noting an outer aggregate on top would
    double-count the same wall time)."""
    with _WINDOW.lock:
        return sum(_WINDOW.calls.values())


def flush_window(wall_s: float, steps: Optional[int] = None,
                 warmup: bool = False, **attrs) -> Optional[dict]:
    """Close the attribution window at a boundary the drive already
    syncs at: ``wall_s`` is the synced wall clock of the window, the
    accumulated dispatch seconds split it into host-dispatch vs
    device-compute.  Emits ``attrib/{dispatch_ms,compute_ms,
    dispatch_frac}`` gauges (lower dispatch_frac is better — a rising
    fraction means the host, not the chip, is the bottleneck).  Warmup
    windows are discarded (their dispatch time is XLA compile).  No-op
    with nothing accumulated or telemetry off."""
    dispatch, calls = _WINDOW.take()
    obs = get_obs()
    n_calls = sum(calls.values())
    if not obs.enabled or warmup or not n_calls or not wall_s > 0:
        return None
    dispatch_s = sum(dispatch.values())
    # clamp: on a synchronous backend (CPU) the dispatch IS the compute
    # and rounding can push the sum a hair past the wall
    dispatch_s = min(dispatch_s, wall_s)
    compute_s = wall_s - dispatch_s
    frac = dispatch_s / wall_s
    steps_attr = {} if steps is None else {"steps": int(steps)}
    names = ",".join(sorted(calls))
    out = {"dispatch_ms": dispatch_s * 1e3, "compute_ms": compute_s * 1e3,
           "dispatch_frac": frac, "calls": n_calls, "wall_ms": wall_s * 1e3,
           "step": names}
    obs.gauge("attrib/dispatch_ms").set(
        round(dispatch_s * 1e3, 3), step=names, calls=n_calls,
        **steps_attr, **attrs)
    obs.gauge("attrib/compute_ms").set(
        round(compute_s * 1e3, 3), step=names, calls=n_calls,
        **steps_attr, **attrs)
    obs.gauge("attrib/dispatch_frac").set(
        round(frac, 6), step=names, calls=n_calls, **steps_attr, **attrs)
    return out


class dispatch_timer:
    """``with dispatch_timer("ae_chunk"): fn(...)`` — time one un-blocked
    dispatch into the open window (the AE engine's chunk loop hook; the
    GAN steps go through ``instrument_step``'s wrapper instead)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        note_dispatch(self.name, time.perf_counter() - self._t0)
        return False


# ------------------------------------------------------- trace digestion
class TraceUnavailable(RuntimeError):
    """A run dir carries no digestible profiler trace — the typed skip
    (``obs profile`` renders it as a skip document, never a crash):
    either the run never captured one (``trace_capture`` is opt-in) or
    the runtime's profiler emitted a format this parser does not read
    (xplane-only exports carry no trace-event JSON)."""


def find_trace_files(run_dir) -> List[Path]:
    """Every perfetto trace-event JSON under the run dir's capture
    roots: the ``traces`` links in ``run.json`` plus the default
    ``<run_dir>/traces`` tree (``**/*.trace.json.gz`` — the layout
    ``jax.profiler`` writes under ``plugins/profile/<session>/``)."""
    run_dir = Path(run_dir)
    roots = [run_dir / "traces"]
    try:
        doc = json.loads((run_dir / "run.json").read_text())
        for link in doc.get("traces") or []:
            if isinstance(link, dict) and link.get("path"):
                roots.append(Path(str(link["path"])))
    except (OSError, json.JSONDecodeError):
        pass
    out: List[Path] = []
    seen = set()
    for root in roots:
        for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
            for p in sorted(glob.glob(str(root / pat), recursive=True)):
                if p not in seen:
                    seen.add(p)
                    out.append(Path(p))
    return out


def load_trace_events(path) -> Tuple[List[Tuple[str, float, float]],
                                     List[str]]:
    """All complete events on device-pid ``XLA Ops`` threads of one
    perfetto trace: ``([(op_name, ts_us, dur_us)], sorted thread names)``
    — the parser ``tools/mfu_trace_probe.py`` carried privately, now the
    one shared implementation."""
    path = Path(path)
    opener = gzip.open if path.name.endswith(".gz") else open
    with opener(path, "rt") as fh:
        tr = json.load(fh)
    ev = tr.get("traceEvents", []) if isinstance(tr, dict) else []
    pid_name, tid_name = {}, {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e.get("pid")] = (e.get("args") or {}).get("name", "")
        elif e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_name[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")
    dev_pids = {p for p, n in pid_name.items()
                if "TPU" in n.upper() or "device" in n.lower()}
    op_tids = {pt for pt, n in tid_name.items()
               if pt[0] in dev_pids and "XLA Ops" in n}
    out = []
    for e in ev:
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in op_tids:
            try:
                out.append((str(e.get("name", "")), float(e["ts"]),
                            float(e.get("dur", 0.0))))
            except (KeyError, TypeError, ValueError):
                continue
    return out, sorted(set(tid_name.values()))


def interval_union_s(events) -> float:
    """Union length of the events' ``[ts, ts+dur)`` intervals in seconds
    — device busy time without double-counting parents (a ``while`` op
    SPANS its body's ops; a fusion wrapper spans its constituents — a
    plain sum counts them twice, the union does not)."""
    ivs = sorted((ts, ts + d) for _, ts, d in events if d > 0)
    total, cur_a, cur_b = 0.0, None, None
    for a, b in ivs:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total * 1e-6                                   # us -> s


def op_table(events, top: int = 20) -> List[dict]:
    """Per-op time table (summed self-reported durations — comparable
    *between* ops; the union above is the honest total), largest
    first."""
    by_op: Dict[str, List[float]] = {}
    for name, _, dur in events:
        by_op.setdefault(name, [0.0, 0])
        by_op[name][0] += dur * 1e-6
        by_op[name][1] += 1
    rows = [{"op": n, "total_s": round(v[0], 9), "n": int(v[1])}
            for n, v in by_op.items()]
    rows.sort(key=lambda r: -r["total_s"])
    return rows[: max(0, int(top))] if top else rows


def region_table(events, regions=(("lstm", ("lstm", "LSTM")),
                                  ("fusion", ("fusion",)),
                                  ("while", ("while",)),
                                  ("custom-call", ("custom-call",)),
                                  ("convolution/dot", ("dot", "conv")),
                                  )) -> List[dict]:
    """Named-region busy time: interval union over the ops whose name
    carries any of the region's substrings (matched events nest — the
    same trap as the total, so each region is its own union)."""
    out = []
    for label, needles in regions:
        matched = [e for e in events
                   if any(n in e[0] for n in needles)]
        if matched:
            out.append({"region": label,
                        "busy_s": round(interval_union_s(matched), 9),
                        "n": len(matched)})
    out.sort(key=lambda r: -r["busy_s"])
    return out


def profile_run(run_dir, top: int = 20) -> dict:
    """Digest every trace a run captured into one per-op/per-region time
    document.  Raises :class:`TraceUnavailable` (typed, for the CLI's
    skip path) when the run carries no digestible trace."""
    run_dir = Path(run_dir)
    files = find_trace_files(run_dir)
    if not files:
        raise TraceUnavailable(
            f"{run_dir}: no trace-event JSON under traces/ or the "
            "manifest's trace links (trace_capture is opt-in, and "
            "xplane-only profiler exports carry no trace.json.gz)")
    captures = []
    parsed_any = False
    for f in files:
        try:
            events, threads = load_trace_events(f)
        except (OSError, json.JSONDecodeError, EOFError) as e:
            captures.append({"file": str(f), "error": str(e)})
            continue
        parsed_any = True
        captures.append({
            "file": str(f),
            "n_events": len(events),
            "busy_s": round(interval_union_s(events), 9),
            "ops": op_table(events, top=top),
            "regions": region_table(events),
            "threads": threads,
        })
    if not parsed_any:
        raise TraceUnavailable(
            f"{run_dir}: {len(files)} trace file(s) present but none "
            "parsed as trace-event JSON")
    return {"run_dir": str(run_dir), "n_traces": len(files),
            "captures": captures}


def render_profile(doc: dict) -> str:
    """Human rendering of :func:`profile_run`'s document."""
    lines = [f"trace profile — {doc['run_dir']} "
             f"({doc['n_traces']} capture(s))"]
    for cap in doc["captures"]:
        if "error" in cap:
            lines.append(f"  {cap['file']}: unreadable ({cap['error']})")
            continue
        lines.append(f"  {cap['file']}")
        lines.append(f"    device busy {cap['busy_s'] * 1e3:.3f} ms "
                     f"(interval union over {cap['n_events']} op events)")
        for r in cap["regions"]:
            lines.append(f"    region {r['region']:16s} "
                         f"{r['busy_s'] * 1e3:10.3f} ms  (n={r['n']})")
        for row in cap["ops"][:10]:
            lines.append(f"    op {row['op'][:48]:48s} "
                         f"{row['total_s'] * 1e3:10.3f} ms  (n={row['n']})")
    return "\n".join(lines)


# ------------------------------------------------------------- CLI entry
def profile_main(run_dir, top: int = 20, fmt: str = "human") -> int:
    """``obs profile RUN_DIR`` — exit 0 with a table/JSON document, or a
    typed skip document (still exit 0: an un-profiled run is a fact, not
    a failure) when the run carries no digestible trace."""
    import sys
    try:
        doc = profile_run(run_dir, top=top)
    except TraceUnavailable as e:
        if fmt == "json":
            print(json.dumps({"run_dir": str(run_dir), "skipped": str(e)}))
        else:
            print(f"profile skipped: {e}", file=sys.stderr)
        return 0
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if fmt == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(render_profile(doc))
    return 0
