"""hfrep_tpu.obs — unified tracing, metrics & device-telemetry layer.

The reference codebase's only observability is ``print`` statements in
its epoch loops (SURVEY §5.5); rounds 1-5 of this port grew two point
tools — a JSONL ``MetricLogger`` and a device-synced ``StepTimer`` —
with nothing connecting the trainer, the parallel launch paths, the
replication engine and the bench probes.  This package is the single
telemetry subsystem behind all of them (the PR-2 shims are retired:
:class:`hfrep_tpu.obs.metriclog.MetricLogger` carries the reference
epoch-echo formats, :class:`hfrep_tpu.obs.timeline.BlockTimer` the
block-boundary timing):

* **spans** — ``with obs.span("compile"): ...`` nested, device-sync-aware
  timings (pass ``sync_on=`` a device array to block on XLA's async
  dispatch before the clock stops);
* **metrics** — one registry of counters / gauges / histograms;
* **wall-clock ledger** — every ms of an instrumented drive assigned to
  exactly one category, Σ(categories) == wall pinned, perfetto-timeline
  reconstruction from the event stream alone
  (:mod:`hfrep_tpu.obs.timeline`; ``python -m hfrep_tpu.obs timeline``);
* **device telemetry** — ``jax.live_arrays()`` / ``memory_stats()``
  snapshots and backend-compile counts via ``jax.monitoring``
  (:mod:`hfrep_tpu.obs.device`);
* **MFU** — analytic FLOPs accounting for the flagship epoch
  (:mod:`hfrep_tpu.obs.flops`, moved from ``tools/flops_accounting.py``);
* **run manifests** — ``run.json`` with git SHA, config, mesh shape,
  jax/flax versions, host info and xprof trace links
  (:mod:`hfrep_tpu.obs.manifest`; captures via :func:`trace_capture`);
* **report CLI** — ``python -m hfrep_tpu.obs report RUN_DIR [RUN_DIR2]``
  summarizes or diffs run directories (:mod:`hfrep_tpu.obs.report`),
  ``report --merge`` folds a multi-host launch's per-process dirs;
* **run history & regression gate** — ``python -m hfrep_tpu.obs gate``
  baselines a run against the append-only history index
  (:mod:`hfrep_tpu.obs.history` / :mod:`hfrep_tpu.obs.regress`:
  median/MAD rolling baselines per (metric, family, mesh, host)).

Design rule — *no-op when disabled*: the module-level singleton starts
as :data:`NULL` (``enabled = False``); every instrumentation hook in
train/, parallel/, replication/ and tools/ goes through :func:`get_obs`
and costs one attribute check when telemetry is off.  Nothing here ever
runs inside ``jit`` — telemetry is host-side only, so enabling it cannot
change a single compiled program or trajectory.

Event stream: ``<run_dir>/events.jsonl``, one JSON object per line,
``{"v": 1, "t": <seconds since run start>, "type": ...}`` with types
``span`` / ``metric`` / ``memory`` / ``event`` — see
:data:`EVENT_TYPES` and ``obs/README.md`` for the field-level schema.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import time
from pathlib import Path
from typing import IO, Dict, List, Optional

SCHEMA_VERSION = 1

#: every ``"type"`` the event stream may carry (the report parser and the
#: ``--self-test`` validate against this set)
EVENT_TYPES = ("span", "metric", "memory", "event")

#: log-bucket resolution of the streaming histogram: buckets per decade.
#: 100 → ~2.3% relative bucket width, ~2.4k live buckets across 1e-12..
#: 1e12 worst case (stored sparsely) — the registry's memory is O(spread)
#: instead of O(samples), so a 100k-request serve run or a week-long soak
#: no longer holds every sample
_HIST_BUCKETS_PER_DECADE = 100


def _json_safe(v):
    """Best-effort conversion so telemetry can never crash a run."""
    if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
        return None          # keep the stream strict JSON (no bare NaN)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    try:
        import numpy as np
        if isinstance(v, (np.generic, np.ndarray)) and np.ndim(v) == 0:
            return np.asarray(v).item()
    except Exception:
        pass
    return str(v)


def mesh_attrs(mesh) -> Optional[Dict[str, int]]:
    """``Mesh -> {"dp": 2, "sp": 4}`` (JSON-safe mesh description)."""
    if mesh is None:
        return None
    return {str(n): int(mesh.shape[n]) for n in mesh.axis_names}


# ------------------------------------------------------------- instruments
class Counter:
    """Monotonic count; every ``inc`` also lands in the event stream."""

    def __init__(self, obs: "Obs", name: str):
        self._obs, self.name, self.value = obs, name, 0

    def inc(self, n: int = 1, **attrs) -> None:
        self.value += n
        self._obs._emit({"type": "metric", "kind": "counter",
                         "name": self.name, "value": self.value,
                         "delta": n, **_json_safe(attrs)})


class Gauge:
    """Last-value-wins measurement (memory bytes, steps/sec, MFU)."""

    def __init__(self, obs: "Obs", name: str):
        self._obs, self.name, self.value = obs, name, None

    def set(self, v, **attrs) -> None:
        self.value = _json_safe(v)
        self._obs._emit({"type": "metric", "kind": "gauge",
                         "name": self.name, "value": self.value,
                         **_json_safe(attrs)})


class Histogram:
    """Bounded log-bucket streaming accumulator.

    The JSONL stream keeps full per-sample fidelity (every ``observe``
    still lands as one metric line); the in-memory registry keeps only
    sparse log-bucket counts + exact n/sum/min/max, so its footprint is
    O(value spread), never O(samples) — the unbounded per-metric sample
    list was the one structure a 100k-request serve run or a long soak
    grew without limit.  Nearest-rank percentiles come back as the
    holding bucket's geometric midpoint, clamped to the observed
    [min, max]: within one bucket width (~2.3% relative,
    :data:`_HIST_BUCKETS_PER_DECADE`) of the exact sample statistic
    (pinned by ``tests/test_obs.py``).
    """

    def __init__(self, obs: "Obs", name: str):
        self._obs, self.name = obs, name
        self.counts: Dict[int, int] = {}    # log-bucket index -> count
        self.n = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._n_zero = 0                    # exactly-0.0 samples
        self._n_neg = 0                     # negative samples (rare)

    def observe(self, v: float, **attrs) -> None:
        v = float(v)
        self.n += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if v > 0.0 and math.isfinite(v):
            idx = math.floor(math.log10(v) * _HIST_BUCKETS_PER_DECADE)
            self.counts[idx] = self.counts.get(idx, 0) + 1
        elif v == 0.0:
            self._n_zero += 1
        else:
            self._n_neg += 1                # negatives + non-finite
        self._obs._emit({"type": "metric", "kind": "histogram",
                         "name": self.name, "value": v,
                         **_json_safe(attrs)})

    def percentile(self, pct: float) -> Optional[float]:
        """Nearest-rank percentile (rank ``ceil(pct/100 · n)`` — the one
        definition the serve loadgen and the report share), resolved to
        the holding bucket's representative value."""
        if self.n == 0:
            return None
        # ceil(pct/100 · n) without int(pct) truncation: percentile(99.9)
        # must resolve the p99.9 rank, not silently return p99
        rank = max(1, math.ceil(self.n * float(pct) / 100.0))
        acc = self._n_neg
        if rank <= acc:
            return self.min
        acc += self._n_zero
        if rank <= acc:
            return 0.0
        for idx in sorted(self.counts):
            acc += self.counts[idx]
            if rank <= acc:
                lo = 10.0 ** (idx / _HIST_BUCKETS_PER_DECADE)
                hi = 10.0 ** ((idx + 1) / _HIST_BUCKETS_PER_DECADE)
                rep = math.sqrt(lo * hi)
                return min(max(rep, self.min), self.max)
        return self.max


class _NullInstrument:
    """Counter/Gauge/Histogram stand-in when telemetry is off."""

    name, value, samples = "null", 0, ()

    def inc(self, n: int = 1, **attrs) -> None: pass
    def set(self, v, **attrs) -> None: pass
    def observe(self, v: float, **attrs) -> None: pass


_NULL_INSTRUMENT = _NullInstrument()
_NULL_CTX = contextlib.nullcontext()


# --------------------------------------------------------------- the sink
class Obs:
    """An enabled telemetry sink bound to one run directory.

    Constructed via :func:`enable` (which also writes the run manifest and
    installs the jax.monitoring compile listener); all writes go through
    :meth:`_emit`, which must never raise into the training loop.
    """

    enabled = True

    def __init__(self, run_dir, flush_every: int = 32,
                 rotate_bytes: Optional[int] = None):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.events_path = self.run_dir / "events.jsonl"
        self._rotate_previous_run()
        self._fh: Optional[IO] = open(self.events_path, "a")
        # writer-side stream rotation (the fleet retention tier): at the
        # threshold the live stream is renamed to the next
        # rollup/chunk-<n>.jsonl and reopened fresh, so a week-long
        # soak's live stream stays bounded and `obs compact` folds the
        # chunks.  0/None = never rotate (the default: short runs keep
        # the one-stream layout every existing reader knows).
        if rotate_bytes is None:
            try:
                rotate_bytes = int(
                    os.environ.get("HFREP_OBS_ROTATE_BYTES") or 0)
            except ValueError:
                rotate_bytes = 0
        self._rotate_bytes = max(0, int(rotate_bytes))
        # fault-injection hook for the append stream (HFREP_FAULTS
        # io_fail@obs_append=N): None unless a plan is active at sink
        # construction, so the per-emit cost stays one `if`.  Only an
        # ImportError (bootstrap ordering) degrades to no-hook — a
        # malformed HFREP_FAULTS spec must raise here as loudly as it
        # does at the first boundary tick, not silently disable every
        # fault in the plan (active_plan caches the env read).
        try:
            from hfrep_tpu.resilience import io_hook
        except ImportError:
            self._io_fault = None
        else:
            self._io_fault = io_hook("obs_append")
        self._flush_every = max(1, flush_every)
        # the wall-clock ledger's self-measurement: every _emit times its
        # own body into the `obs_self` category, so `timeline/obs_self_frac`
        # is measured by the same plane it polices (cached module ref —
        # obs is fully imported by construction time, so no cycle)
        from hfrep_tpu.obs import timeline as _timeline
        self._timeline = _timeline
        self._t0 = time.perf_counter()
        self._stack: List[str] = []          # open span names (nesting)
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._n_events = 0

    # ------------------------------------------------------------- plumbing
    def _rotate_previous_run(self) -> None:
        """A run dir holds ONE run: re-using it must not merge two runs'
        statistics (run.json is overwritten; a merged events.jsonl would
        silently blend both runs' steps/sec and compile counts in the
        report).  A previous non-empty stream is rotated aside to
        ``events-<n>.jsonl``; the report reads only ``events.jsonl``."""
        try:
            if not (self.events_path.exists()
                    and self.events_path.stat().st_size > 0):
                return
            n = 1
            while (self.run_dir / f"events-{n}.jsonl").exists():
                n += 1
            self.events_path.rename(self.run_dir / f"events-{n}.jsonl")
        except OSError:
            pass                       # worst case: the old append behavior

    def now(self) -> float:
        return time.perf_counter() - self._t0

    def _emit(self, rec: dict) -> None:
        if self._fh is None:
            return
        t_emit = time.perf_counter()
        rec = {"v": SCHEMA_VERSION, "t": round(self.now(), 6), **rec}
        try:
            if self._io_fault is not None:
                self._io_fault()
            self._fh.write(json.dumps(rec, default=str) + "\n")
            self._n_events += 1
            if self._n_events % self._flush_every == 0:
                self._fh.flush()
                if (self._rotate_bytes
                        and self._fh.tell() >= self._rotate_bytes):
                    self._rotate_live()
        except (OSError, ValueError):       # telemetry must not kill a run
            pass
        finally:
            # pure accumulator arithmetic — no emit, so no recursion
            self._timeline.note_obs_self(time.perf_counter() - t_emit)

    def _rotate_live(self) -> None:
        """Writer-side rotation: flush + close the live stream, rename
        it to the next rollup chunk (``obs compact`` folds those into
        segments + pinned evidence), reopen fresh.  Only the writer can
        do this safely — an external rename would leave this process
        appending to the renamed file through its held handle.
        Best-effort like every other telemetry write: the worst failure
        mode is the old unbounded-stream behavior."""
        fh, self._fh = self._fh, None
        try:
            fh.flush()
            fh.close()
        except OSError:
            pass
        try:
            from hfrep_tpu.obs import rollup as _rollup
            chunk_dir = self.run_dir / _rollup.ROLLUP_DIR
            chunk_dir.mkdir(parents=True, exist_ok=True)
            if (self.events_path.exists()
                    and self.events_path.stat().st_size > 0):
                self.events_path.rename(
                    chunk_dir
                    / f"chunk-{_rollup.next_chunk_index(self.run_dir)}.jsonl")
        except OSError:
            pass
        try:
            self._fh = open(self.events_path, "a")
        except OSError:
            self._fh = None

    def flush(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
            except OSError:
                pass

    def close(self) -> None:
        """Idempotent: emits the registry summary once, then closes."""
        if self._fh is None:
            return
        self._emit({"type": "event", "name": "run_end",
                    "summary": self.summary()})
        fh, self._fh = self._fh, None
        try:
            fh.flush()
            fh.close()
        except OSError:
            pass

    # ---------------------------------------------------------------- spans
    @contextlib.contextmanager
    def span(self, name: str, sync_on=None, **attrs):
        """Nested timing block.  ``sync_on`` takes a (pytree of) device
        array(s) to ``jax.block_until_ready`` before the clock stops —
        without it an async-dispatched step would time only its launch."""
        parent = self._stack[-1] if self._stack else None
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            synced = sync_on is not None
            if synced:
                try:
                    import jax
                    jax.block_until_ready(sync_on)
                except Exception:
                    synced = False
            dur = time.perf_counter() - t0
            self._stack.pop()
            self._emit({"type": "span", "name": name, "dur": round(dur, 6),
                        "depth": len(self._stack), "parent": parent,
                        "synced": synced, **_json_safe(attrs)})

    def record_span(self, name: str, dur: float, **attrs) -> None:
        """A span whose duration was measured elsewhere (e.g. BlockTimer's
        already-device-synced windows) — same schema, no re-timing."""
        parent = self._stack[-1] if self._stack else None
        self._emit({"type": "span", "name": name, "dur": round(float(dur), 6),
                    "depth": len(self._stack), "parent": parent,
                    **_json_safe(attrs)})

    # -------------------------------------------------------------- metrics
    def counter(self, name: str) -> Counter:
        return self._counters.setdefault(name, Counter(self, name))

    def gauge(self, name: str) -> Gauge:
        return self._gauges.setdefault(name, Gauge(self, name))

    def histogram(self, name: str) -> Histogram:
        return self._histograms.setdefault(name, Histogram(self, name))

    def event(self, name: str, **attrs) -> None:
        """Free-form structured event (``parallel_build``, ``train_start``)."""
        self._emit({"type": "event", "name": name, **_json_safe(attrs)})

    def summary(self) -> dict:
        """Registry state as plain data (also the ``run_end`` payload).
        Histogram percentiles are log-bucket resolved (within one bucket
        width of the exact nearest-rank statistic); ``max`` is exact."""
        hist = {name: {"n": h.n,
                       "p50": _json_safe(h.percentile(50)),
                       "p95": _json_safe(h.percentile(95)),
                       "max": _json_safe(h.max)}
                for name, h in self._histograms.items()}
        return {"counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": hist}

    # ----------------------------------------------------- device telemetry
    def memory_snapshot(self, **attrs) -> None:
        from hfrep_tpu.obs import device
        device.memory_snapshot(self, **attrs)

    # ------------------------------------------------------------- manifest
    def annotate(self, **fields) -> None:
        """Merge fields into this run's ``run.json`` (e.g. the trainer's
        config and mesh, known only after :func:`enable` ran)."""
        from hfrep_tpu.obs import manifest
        manifest.annotate(self.run_dir, {k: _json_safe(v)
                                         for k, v in fields.items()})


class _NullObs:
    """The disabled singleton: every hook is one attribute check away
    from free.  ``span`` hands back a shared ``nullcontext``."""

    enabled = False
    run_dir = None

    def span(self, name: str, sync_on=None, **attrs):
        return _NULL_CTX

    def record_span(self, name: str, dur: float, **attrs) -> None: pass
    def event(self, name: str, **attrs) -> None: pass
    def counter(self, name: str): return _NULL_INSTRUMENT
    def gauge(self, name: str): return _NULL_INSTRUMENT
    def histogram(self, name: str): return _NULL_INSTRUMENT
    def memory_snapshot(self, **attrs) -> None: pass
    def annotate(self, **fields) -> None: pass
    def summary(self) -> dict: return {}
    def flush(self) -> None: pass
    def close(self) -> None: pass
    def now(self) -> float: return 0.0


NULL = _NullObs()
_active: Optional[Obs] = None


def get_obs():
    """The active sink, or :data:`NULL` — the one hook every instrumented
    call site uses; never returns None."""
    return _active if _active is not None else NULL


def is_enabled() -> bool:
    return _active is not None


def enable(run_dir, *, manifest: bool = True, compile_listener: bool = True,
           rotate_bytes: Optional[int] = None, **manifest_extra) -> Obs:
    """Activate telemetry into ``run_dir`` (closing any previous sink).

    Writes ``run.json`` immediately (git SHA, versions, host, devices;
    callers merge config/mesh later via :meth:`Obs.annotate`) and installs
    the ``jax.monitoring`` backend-compile listener.  ``rotate_bytes``
    arms writer-side live-stream rotation for long soaks (default: the
    ``HFREP_OBS_ROTATE_BYTES`` env knob; see :class:`Obs`).
    """
    global _active
    if _active is not None:
        disable()
    # a fresh run arms a fresh wall-clock ledger: the previous run's
    # cumulative category fractions must not bleed into this one's gauges
    from hfrep_tpu.obs import timeline
    timeline.reset()
    obs = Obs(run_dir, rotate_bytes=rotate_bytes)
    _active = obs
    try:
        if manifest:
            from hfrep_tpu.obs import manifest as mf
            mf.write_manifest(obs.run_dir, extra=manifest_extra or None)
        if compile_listener:
            from hfrep_tpu.obs import device
            device.install_compile_listener(obs)
        obs.event("run_start")
    except BaseException:
        # a partial enable (events stream opened, manifest write raised)
        # must not leave the half-open sink as the active singleton —
        # callers that catch the error and degrade to telemetry-off
        # would otherwise keep emitting through it, unclosed, forever
        disable()
        raise
    return obs


def disable() -> None:
    """Close the active sink and return to the no-op singleton."""
    global _active
    if _active is None:
        return
    from hfrep_tpu.obs import device
    device.remove_compile_listener(_active)
    _active.close()
    _active = None


@contextlib.contextmanager
def session(run_dir, **manifest_extra):
    """The whole enable/disable lifecycle as one context manager — the
    single implementation behind the CLIs and bench probes.  A falsy
    ``run_dir`` yields the :data:`NULL` sink (telemetry stays off, every
    hook a no-op); otherwise the run_end summary, flush and close are
    guaranteed even when the body raises, and the report hint is printed
    on the way out.

    Flight recorder: any exception that escapes the body lands a
    crash-forensics bundle (last-N events, manifest, env, traceback) as
    an atomic ``crash_<run_id>/`` directory under the run dir
    (:mod:`hfrep_tpu.obs.crash`), so "what was the system doing when it
    died" survives the death.  A clean ``SystemExit(0)`` does not
    bundle.  Drains the body HANDLES (the CLIs catch Preempted and
    return exit 75) bundle explicitly at the handler via
    :func:`hfrep_tpu.obs.crash.bundle_if_enabled` — a drive that
    recovers from a Preempted and completes cleanly (the walk-forward
    drill's injected-preempt→resume path) must NOT leave a crash bundle
    for a successful run.
    """
    if not run_dir:
        yield NULL
        return
    obs = enable(run_dir, **manifest_extra)
    try:
        yield obs
    except BaseException as e:
        if not (isinstance(e, SystemExit) and e.code in (0, None)):
            from hfrep_tpu.obs import crash
            crash.write_crash_bundle(obs, e)
        raise
    finally:
        disable()
        # stderr, not stdout: the bench probes' single-JSON-line stdout
        # contract (and any CLI's --format json) must stay machine-pure
        import sys
        print(f"telemetry: {run_dir} "
              f"(python -m hfrep_tpu.obs report {run_dir})", file=sys.stderr)


@contextlib.contextmanager
def session_or_off(run_dir, prog: str, **manifest_extra):
    """:func:`session` that degrades to telemetry-off instead of raising
    when the run dir is unusable (unwritable path, ``run.json`` blocked):
    the bench probes' contract is that telemetry must never cost the
    measurement or the stdout JSON line, so the failure becomes a stderr
    notice and the :data:`NULL` sink.  Callers that gate on the run dir
    afterwards should check ``obs.enabled``.  ``prog`` prefixes the
    notice (the only thing the probes were duplicating)."""
    with contextlib.ExitStack() as stack:
        try:
            obs = stack.enter_context(session(run_dir, **manifest_extra))
        except OSError as e:
            import sys
            print(f"{prog}: telemetry disabled (run dir {run_dir}: {e})",
                  file=sys.stderr)
            obs = stack.enter_context(session(None))
        yield obs


@contextlib.contextmanager
def trace_capture(log_dir=None, **attrs):
    """Capture a jax.profiler (xprof/XLA) trace AND link it into the run.

    Wraps ``jax.profiler.start_trace`` / ``stop_trace`` so on-chip
    profiling joins the telemetry stream instead of living beside it
    (the ROADMAP xprof-linkage gap): with obs enabled the capture lands
    under ``<run_dir>/traces`` by default, a ``trace_capture`` event
    enters the stream, and ``run.json`` gains a ``traces`` entry
    (path, file count, wall seconds) so the report side can find every
    capture a run produced.  With obs disabled an explicit ``log_dir``
    still captures (plain profiling keeps working); no dir at all is a
    no-op.

    Capture failures propagate — the user asked for a profile, unlike
    passive telemetry — but the manifest/stream linkage is best-effort.
    Yields the capture directory (or None when inactive).
    """
    obs = get_obs()
    if log_dir is None:
        if not obs.enabled:
            yield None
            return
        log_dir = Path(obs.run_dir) / "traces"
    log_dir = Path(log_dir)
    import jax
    # Snapshot what's already under the capture root: repeated captures
    # into the shared default <run_dir>/traces must each report only the
    # files THEY produced, not the cumulative pile.  Only the linkage
    # branch reads the count, so a disabled-obs capture into a big
    # profile root skips both directory walks.
    pre = _trace_file_set(log_dir) if obs.enabled else frozenset()
    t0 = time.perf_counter()
    jax.profiler.start_trace(str(log_dir))
    try:
        yield str(log_dir)
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            dur = time.perf_counter() - t0
            if obs.enabled:
                n = _count_trace_files(log_dir, exclude=pre)
                obs.event("trace_capture", path=str(log_dir), n_traces=n,
                          secs=round(dur, 6), **_json_safe(attrs))
                from hfrep_tpu.obs import manifest as mf
                mf.add_trace_link(obs.run_dir, str(log_dir), n_traces=n,
                                  secs=round(dur, 6))


def _trace_file_set(log_dir) -> frozenset:
    """Every file currently under the capture root (empty when the dir
    doesn't exist yet) — the pre-capture snapshot ``_count_trace_files``
    subtracts so each capture reports its own output."""
    try:
        return frozenset(p for p in Path(log_dir).rglob("*") if p.is_file())
    except OSError:
        return frozenset()


def _count_trace_files(log_dir, exclude: frozenset = frozenset()) -> int:
    """How many xplane captures landed (every host/session writes one
    ``*.xplane.pb``); falls back to a raw file count for older runtimes
    that only emit ``trace.json.gz``.  ``exclude`` holds files from
    earlier captures into the same root."""
    try:
        new = [p for p in Path(log_dir).rglob("*")
               if p.is_file() and p not in exclude]
    except OSError:
        return 0
    xplanes = [p for p in new if p.name.endswith(".xplane.pb")]
    return len(xplanes) or len(new)


def maybe_enable_from_env() -> Optional[Obs]:
    """Honor ``HFREP_OBS_DIR`` so CLIs and bench probes opt in without
    threading a flag through every entry point."""
    import os
    run_dir = os.environ.get("HFREP_OBS_DIR")
    if run_dir and not is_enabled():
        return enable(run_dir)
    return None


def instrument_step(fn, name: str, mesh=None, **attrs):
    """Wrap a built (jitted) step for telemetry — the parallel launch
    paths' hook.  Decided at BUILD time: when telemetry is off this
    returns ``fn`` unchanged, so the hot path carries zero wrapper frames.

    When on: emits a ``parallel_build`` event, records the first call as
    a device-synced ``compile:<name>`` span (first call pays trace +
    XLA compile) — also fingerprinting the lowered program against the
    first call's operands (``program_profile`` event + ``run.json``
    ``programs`` entry, hfrep_tpu/obs/attrib.py; graceful no-op where
    the callable or runtime cannot lower) — and counts subsequent
    dispatches (un-synced — counting must not serialize the trainer's
    block pipelining) while accumulating their un-blocked host-side
    durations into the attribution window ``BlockTimer.stop`` flushes at
    the block boundaries the trainer already syncs at (the
    dispatch-vs-compute split; zero per-call events, zero new syncs).
    """
    obs = get_obs()
    if not obs.enabled:
        return fn
    obs.event("parallel_build", step=name, mesh=mesh_attrs(mesh),
              **_json_safe(attrs))
    state = {"first": True}

    def wrapped(*args, **kwargs):
        from hfrep_tpu.obs import attrib
        if state["first"]:
            state["first"] = False
            # fingerprint BEFORE executing: the jitted step may donate
            # its input buffers, and lowering only reads avals anyway
            from hfrep_tpu.obs import timeline
            # program fingerprinting is obs-only work (it does not run
            # with telemetry off), so its lowering cost books as the
            # obs layer's own overhead
            with timeline.timed("obs_self"):
                attrib.profile_jitted(fn, f"compile:{name}", *args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            try:
                import jax
                jax.block_until_ready(out)
            except Exception:
                pass
            dur = time.perf_counter() - t0
            obs.record_span(f"compile:{name}", dur, synced=True)
            # the warmup ledger window's dominant cost: trace + XLA
            # compile + the synced first execution, booked as dispatch
            # (warmup windows' dispatch includes compile by contract)
            timeline.account("dispatch", dur)
            return out
        obs.counter(f"dispatch:{name}").inc()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        attrib.note_dispatch(name, time.perf_counter() - t0)
        return out

    wrapped.__wrapped__ = fn
    wrapped.__name__ = f"obs_instrumented_{name}"
    return wrapped


def instrument_launch(fn, name: str, mesh=None, tcfg=None, jit: bool = True,
                      sp: bool = False, **attrs):
    """The ONE launch-factory wrapper over :func:`instrument_step` —
    shared by every parallel step builder (dp, sp, tp, dp×sp, dp×tp,
    dp×sp×tp, pp) so the hook contract cannot drift between them.

    ``jit=False`` (a composition-internal raw step that a later builder
    will wrap) returns ``fn`` unchanged, like the disabled-telemetry
    case.  ``tcfg`` contributes the batch size, plus the sp pipeline
    knobs when ``sp=True``; extra attrs ride through to the
    ``parallel_build`` event.
    """
    if not jit:
        return fn
    if tcfg is not None:
        attrs.setdefault("batch", tcfg.batch_size)
        if sp:
            attrs.setdefault("sp_microbatches", tcfg.sp_microbatches)
            attrs.setdefault("sp_remat", tcfg.sp_remat)
    return instrument_step(fn, name, mesh=mesh, **attrs)
