"""Crash forensics: the flight recorder's black box.

When a run dies — a :class:`~hfrep_tpu.resilience.Preempted` drain, a
typed :class:`~hfrep_tpu.serve.admission.WorkerFault` /
:class:`~hfrep_tpu.obs.health.NumericFault`, or any uncaught exception
escaping :func:`hfrep_tpu.obs.session` — the question "what was the
system doing when it died" must be answerable from disk, not from a
scrollback buffer that evaporated with the terminal.
:func:`write_crash_bundle` captures exactly that, atomically:

``<run_dir>/crash_<run_id>/``
    ``crash.json``        exception type/message + typed-field dump
                          (site, epoch, snapshot, request id...), unix
                          time, pid, argv
    ``traceback.txt``     the full traceback (when one is live)
    ``events_tail.jsonl`` the last :data:`TAIL_EVENTS` lines of every
                          ``events*.jsonl`` in the run dir (rotated
                          streams included — a restarted member's
                          pre-kill history matters most)
    ``env.json``          the process environment, secret-shaped values
                          redacted
    ``run.json``          a copy of the run manifest

Published through :func:`hfrep_tpu.utils.checkpoint.write_atomic` when
available (checksum'd meta, single-rename publish; a second crash in the
same run dir overwrites, keeping the previous bundle as the ``.prev``
sibling) with a stdlib tmp-dir + ``os.replace`` fallback — and strictly
best-effort: forensics must never mask the failure they describe.
``python -m hfrep_tpu.obs report --crash <run_dir>`` reads it back;
``crash-drill`` (wired into ``tools/check.sh``) proves the whole loop
under injected ``io_fail`` + nonfinite faults.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
import traceback
from pathlib import Path
from typing import List, Optional

CRASH_PREFIX = "crash_"
TAIL_EVENTS = 200

#: env keys whose VALUES are redacted in the bundle (the keys survive —
#: knowing a credential was set is diagnostic, its value is not)
_SECRET_RE = re.compile(r"(key|token|secret|passw|credential|auth)",
                        re.IGNORECASE)


def _redacted_env() -> dict:
    return {k: ("<redacted>" if _SECRET_RE.search(k) else v)
            for k, v in sorted(os.environ.items())}


def _tail_lines(path: Path, n: int) -> List[str]:
    try:
        with open(path, errors="replace") as fh:
            return fh.readlines()[-n:]
    except OSError:
        return []


def _exc_doc(exc: BaseException) -> dict:
    doc = {"type": type(exc).__name__, "message": str(exc)}
    # typed exceptions (Preempted, NumericFault, WorkerFault...) carry
    # their context as attributes — dump the JSON-safe ones
    for k, v in sorted(getattr(exc, "__dict__", {}).items()):
        if isinstance(v, (str, int, float, bool)) or v is None:
            doc[k] = v
    return doc


def write_crash_bundle(obs, exc: BaseException,
                       tail_events: int = TAIL_EVENTS) -> Optional[str]:
    """Bundle the run's last moments next to its telemetry; returns the
    bundle path (or None when nothing could be written).  Never raises."""
    try:
        run_dir = Path(obs.run_dir)
        try:
            obs.event("crash_bundle", exception=type(exc).__name__)
            obs.flush()
        except Exception:
            pass
        run_id = run_dir.name
        bundle = run_dir / f"{CRASH_PREFIX}{run_id}"

        from hfrep_tpu.obs.report import is_stream_file
        streams = sorted(f for f in run_dir.glob("events*.jsonl")
                         if is_stream_file(f))
        tails: List[str] = []
        for stream in streams:
            if len(streams) > 1:
                tails.append(f"# stream: {stream.name}\n")
            tails.extend(_tail_lines(stream, tail_events))
        crash_doc = json.dumps(
            {"v": 1, **_exc_doc(exc), "time_unix": round(time.time(), 3),
             "pid": os.getpid(), "argv": list(sys.argv),
             "run_id": run_id}, indent=2, default=str)
        tb = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__)) or f"{type(exc).__name__}: {exc}\n"
        env_doc = json.dumps(_redacted_env(), indent=2, default=str)
        try:
            manifest = (run_dir / "run.json").read_text()
        except OSError:
            manifest = "{}"

        def writer(tmp: Path) -> None:
            (tmp / "crash.json").write_text(crash_doc)
            (tmp / "traceback.txt").write_text(tb)
            (tmp / "events_tail.jsonl").write_text("".join(tails))
            (tmp / "env.json").write_text(env_doc)
            (tmp / "run.json").write_text(manifest)

        path = _publish(bundle, writer, exc)
        if path is not None:
            print(f"crash bundle: {path} "
                  f"(python -m hfrep_tpu.obs report --crash {run_dir})",
                  file=sys.stderr)
        return path
    except Exception:
        return None


def _publish(bundle: Path, writer, exc: BaseException) -> Optional[str]:
    """Atomic publication: the checkpoint writer when importable (it
    needs jax), else a stdlib tmp-dir + single ``os.replace``."""
    try:
        from hfrep_tpu.utils import checkpoint as ckpt
    except Exception:
        ckpt = None
    if ckpt is not None:
        ckpt.write_atomic(bundle, writer,
                          metadata={"kind": "crash_bundle",
                                    "exception": type(exc).__name__},
                          keep_prev=True)
        return str(bundle)
    import shutil
    tmp = bundle.with_name(f".{bundle.name}.tmp-{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    tmp.mkdir(parents=True)
    writer(tmp)
    shutil.rmtree(bundle, ignore_errors=True)
    os.replace(tmp, bundle)
    return str(bundle)


def bundle_if_enabled(exc: BaseException) -> Optional[str]:
    """The CLIs' exit-75 hook: land a crash bundle for a Preempted the
    handler is about to convert into a resumable exit — when telemetry
    is on.  (Session exit already bundles ESCAPED exceptions; this
    covers drains that end the run but never escape as exceptions.
    A drive that catches a Preempted and successfully resumes simply
    does not call this.)"""
    try:
        from hfrep_tpu.obs import get_obs
        obs = get_obs()
        if obs.enabled:
            return write_crash_bundle(obs, exc)
    except Exception:
        pass
    return None


# ---------------------------------------------------------------- reading
def find_bundle(path) -> Optional[Path]:
    """``path`` is a bundle dir, or a run dir holding one (newest wins)."""
    p = Path(path)
    if (p / "crash.json").exists():
        return p
    candidates = sorted((d for d in p.glob(f"{CRASH_PREFIX}*")
                         if (d / "crash.json").exists()),
                        key=lambda d: d.stat().st_mtime)
    return candidates[-1] if candidates else None


def render_bundle(bundle: Path, tb_lines: int = 25,
                  tail_lines: int = 5) -> str:
    """Human rendering for ``report --crash``: the exception, its typed
    context, the traceback tail, and the last few events."""
    try:
        doc = json.loads((bundle / "crash.json").read_text())
    except (OSError, json.JSONDecodeError) as e:
        return f"unreadable crash bundle {bundle}: {e}"
    when = time.strftime("%Y-%m-%dT%H:%M:%S",
                         time.localtime(doc.get("time_unix") or 0))
    lines = [f"crash bundle {bundle}",
             f"  run {doc.get('run_id')}  pid {doc.get('pid')}  {when}",
             f"  {doc.get('type')}: {doc.get('message')}"]
    extras = {k: v for k, v in doc.items()
              if k not in ("v", "type", "message", "time_unix", "pid",
                           "argv", "run_id") and v is not None}
    if extras:
        lines.append("  context: " + ", ".join(
            f"{k}={v}" for k, v in sorted(extras.items())))
    tb = _tail_lines(bundle / "traceback.txt", tb_lines)
    if tb:
        lines.append("  traceback (tail):")
        lines.extend("    " + ln.rstrip("\n") for ln in tb)
    tail = [ln for ln in _tail_lines(bundle / "events_tail.jsonl", tail_lines)
            if not ln.startswith("#")]
    if tail:
        lines.append(f"  last events ({len(tail)} of the bundled tail):")
        lines.extend("    " + ln.rstrip("\n") for ln in tail)
    return "\n".join(lines)


REQUIRED_FILES = ("crash.json", "traceback.txt", "events_tail.jsonl",
                  "env.json", "run.json")


def verify_bundle(bundle: Path) -> List[str]:
    """Missing-piece list (empty = complete) — the drill's assertion."""
    return [f for f in REQUIRED_FILES if not (Path(bundle) / f).exists()]


# ------------------------------------------------------------------ drill
def drill() -> int:
    """``python -m hfrep_tpu.obs crash-drill`` — the CI gate for the
    whole forensics loop (tools/check.sh): a REAL obs session drives a
    REAL (tiny) AE training on NaN-poisoned data with the health
    tripwire armed and ``io_fail@obs_append`` faults injected into the
    event stream; the resulting :class:`~hfrep_tpu.obs.health.
    NumericFault` must land a complete, checksum-verifying crash bundle
    plus the forensic carry dump, and ``report --crash`` must render it.
    One JSON line on stdout; exit 0 = every assertion held.
    """
    import tempfile

    import hfrep_tpu.obs as obs_pkg
    from hfrep_tpu import resilience as res
    from hfrep_tpu.obs import health as health_mod

    problems: List[str] = []
    doc: dict = {"metric": "crash_drill"}
    health_mod.configure(
        health_mod.HealthConfig(abort_on_nonfinite=True))
    with tempfile.TemporaryDirectory(prefix="hfrep_crash_drill_") as td:
        run_dir = Path(td) / "run"
        # the append-stream fault hook resolves at sink construction, so
        # the plan must be live before the session opens: two injected
        # EIOs land mid-stream and the bundle must still publish whole
        res.install_plan(res.FaultPlan.parse("io_fail@obs_append=2x2"))
        caught: Optional[BaseException] = None
        try:
            try:
                with obs_pkg.session(run_dir, command="crash-drill"):
                    import jax
                    import jax.numpy as jnp
                    import numpy as np

                    from hfrep_tpu.config import AEConfig
                    from hfrep_tpu.replication.engine import (
                        train_autoencoder_chunked,
                    )

                    xs = jnp.asarray(
                        np.full((40, 4), np.nan, np.float32))
                    cfg = AEConfig(n_factors=4, latent_dim=2, epochs=4,
                                   batch_size=16, patience=2,
                                   chunk_epochs=2)
                    train_autoencoder_chunked(jax.random.PRNGKey(0), xs,
                                              cfg)
            except health_mod.NumericFault as e:
                caught = e
        finally:
            res.clear_plan()
            health_mod.configure(None)

        if caught is None:
            problems.append("NumericFault never fired on NaN data")
        elif not caught.dump or not Path(caught.dump).exists():
            problems.append(f"forensic dump missing: {caught.dump!r}")
        bundle = find_bundle(run_dir)
        if bundle is None:
            problems.append("no crash bundle under the run dir")
        else:
            missing = verify_bundle(bundle)
            if missing:
                problems.append(f"bundle incomplete: missing {missing}")
            try:
                from hfrep_tpu.utils import checkpoint as ckpt
                ckpt.verify(bundle)
            except Exception as e:
                problems.append(f"bundle failed verification: {e}")
            try:
                crash_doc = json.loads((bundle / "crash.json").read_text())
                if crash_doc.get("type") != "NumericFault":
                    problems.append(
                        f"bundle recorded {crash_doc.get('type')!r}, "
                        "expected NumericFault")
                doc["bundled_exception"] = crash_doc.get("type")
            except (OSError, json.JSONDecodeError) as e:
                problems.append(f"unreadable crash.json: {e}")
            if (bundle / "events_tail.jsonl").exists() and not (
                    bundle / "events_tail.jsonl").read_text().strip():
                problems.append("bundled event tail is empty")
            rendered = render_bundle(bundle)
            if "NumericFault" not in rendered:
                problems.append("report --crash rendering lacks the fault")
            doc["rendered_lines"] = rendered.count("\n") + 1

    doc["self_check"] = "ok" if not problems else "; ".join(problems)
    print(json.dumps(doc))
    if problems:
        print(f"crash-drill FAILED: {'; '.join(problems)}", file=sys.stderr)
        return 1
    return 0
