"""Device telemetry: memory snapshots and compile accounting.

Two sources, both host-side and allocation-free on the device:

* :func:`memory_snapshot` — per-device ``memory_stats()`` (TPU/GPU; CPU
  returns nothing useful) plus a ``jax.live_arrays()`` census, emitted as
  one ``memory`` event.  ``high_water`` is the per-snapshot max of peak
  bytes-in-use across devices, falling back to live-array bytes where the
  allocator exposes no stats — the report's "memory high-water" column is
  the max over these events.
* :func:`install_compile_listener` — ``jax.monitoring`` hooks counting
  backend compiles (``/jax/core/compile/backend_compile_duration``, also
  summed into a total-compile-seconds gauge) and persistent-cache
  requests/hits.  A steady-state trainer should show the compile counter
  flat after warmup; a growing counter is a retracing bug the event
  stream now catches (compare ADVICE.md's recompile pitfalls).
"""

from __future__ import annotations

from typing import Dict

#: monitoring event suffix → counter name in the obs registry
_COMPILE_EVENTS: Dict[str, str] = {
    "/jax/compilation_cache/cache_hits": "compile_cache_hits",
    "/jax/compilation_cache/compile_requests_use_cache":
        "compile_cache_requests",
}
_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"

#: the ONE process-global forwarding listener pair.  jax.monitoring's
#: listener lists are process-global with no public unregister API (the
#: helpers live in ``jax._src``), so per-(obs) registration would leak a
#: dead callback pair on every enable/disable cycle; instead the pair is
#: registered once and forwards to whichever sink is currently active
#: and opted in — inert otherwise.
_FORWARDERS: dict = {}


def memory_snapshot(obs, **attrs) -> None:
    """Emit one ``memory`` event describing every local device now."""
    try:
        import jax
        live = jax.live_arrays()
        live_bytes = int(sum(int(getattr(a, "nbytes", 0) or 0) for a in live))
        devices = []
        high = 0
        for d in jax.local_devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            in_use = stats.get("bytes_in_use")
            peak = stats.get("peak_bytes_in_use")
            devices.append({"id": str(d),
                            "bytes_in_use": in_use, "peak_bytes_in_use": peak})
            high = max(high, int(peak or in_use or 0))
        obs._emit({"type": "memory",
                   "live_arrays": len(live), "live_bytes": live_bytes,
                   "high_water": max(high, live_bytes) or live_bytes,
                   "devices": devices, **attrs})
    except Exception:                 # telemetry must never kill the run
        pass


def _target():
    """The currently active sink, iff it opted into compile accounting."""
    from hfrep_tpu.obs import get_obs
    obs = get_obs()
    if getattr(obs, "_wants_compile_events", False) and obs._fh is not None:
        return obs
    return None


def install_compile_listener(obs) -> None:
    """Route jax.monitoring compile events into ``obs``'s registry.

    Registers the global forwarding pair on first use; later calls (and
    :func:`remove_compile_listener`) only flip the sink's opt-in flag, so
    the process-global listener lists hold a constant two entries no
    matter how many enable/disable cycles a long-lived process runs."""
    obs._wants_compile_events = True
    if _FORWARDERS:
        return
    try:
        import jax.monitoring as monitoring
    except Exception:
        return

    def on_event(event: str, **kw) -> None:
        name = _COMPILE_EVENTS.get(event)
        sink = _target()
        if name is not None and sink is not None:
            sink.counter(name).inc()

    def on_duration(event: str, duration: float, **kw) -> None:
        sink = _target()
        if event == _COMPILE_DURATION_EVENT and sink is not None:
            sink.counter("backend_compiles").inc(seconds=round(duration, 4))
            g = sink.gauge("backend_compile_secs_total")
            g.set(round((g.value or 0.0) + duration, 4))

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)
    _FORWARDERS["event"], _FORWARDERS["duration"] = on_event, on_duration


def remove_compile_listener(obs) -> None:
    obs._wants_compile_events = False
