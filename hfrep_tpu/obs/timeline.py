"""Wall-clock ledger: conservation-law time accounting for every drive.

PR 13's ``attrib/{dispatch_ms,compute_ms}`` split answers "host or
chip?"; nothing in the repo could say where the REST of a drive's wall
clock goes — snapshot I/O? queue waits? the obs layer itself?
un-overlapped boundary syncs?  This module is the missing plane: every
millisecond of an instrumented drive's wall time is assigned to exactly
one category of :data:`CATEGORIES`, and

    Σ(category ms) == window wall ms

is a pinned ledger invariant (the serve layer's submitted==terminal,
applied to time).  Three moving parts:

* **the accumulator** — a lock-guarded, per-process category ledger fed
  by :func:`timed` / :func:`account` / :func:`note_obs_self`.  Nested
  :func:`timed` frames account EXCLUSIVE (self) time — a ``host_io``
  block wrapping a ``checkpoint`` write books only its own milliseconds,
  so nesting can never double-count.  Accumulation is pure host-side
  arithmetic: no events, no syncs, no device work.
* **window flushes** — :func:`flush_window` closes the ledger at a
  boundary the drive ALREADY syncs at (the trainer's block stop, the AE
  engine's chunk boundary), emitting one ``timeline_window`` event
  (pinned verbatim by ``obs compact`` — event records survive
  compaction whole) plus cumulative ``timeline/*`` gauges.  The
  residual ``wall − Σ(measured)`` lands in ``unattributed`` — never
  negative (oversums are proportionally clamped and flagged), so the
  invariant holds by construction.  Zero new device syncs: the boundary
  sync duration is MEASURED here (that is the ``device_compute``
  category — host time blocked on the device), not added.
* **reconstruction** — :func:`build_trace` renders any run dir's event
  stream as a Chrome-trace/perfetto ``trace.json`` (no chip capture
  needed), and :func:`ledger_from_events` re-derives the whole-run
  ledger from the ``timeline_window`` records.  Both consume only
  records ``rollup.pin_record`` preserves verbatim, so their output is
  byte-identical on a rotated+compacted run dir vs the raw original
  (the PR-17 equivalence discipline), and a torn tail (SIGKILL) only
  shrinks the covered window set — the gap degrades into a larger
  ``unattributed`` bucket, never a crash or a miscount.

``timeline/obs_self_frac`` makes the obs layer prove its own overhead:
``Obs._emit`` times itself into the ``obs_self`` category, and the
``--self-test`` gate enforces < 1% on the committed fixture.

Reading ``unattributed`` on a host with fewer cores than XLA wants
(the 1-core CI container is the extreme): XLA's CPU compute threads
preempt the host thread at arbitrary bytecode positions, so device
compute that OVERLAPS the instrumented host code steals wall time
from *inside* otherwise-cheap host sections — it surfaces as an
unattributed residual that migrates when instrumentation changes the
scheduling, and no host-side probe can pin it to a category without
device counters.  That residual is the measurement working as designed
(the books still close; the gate still bounds it); on a real TPU host
the host thread runs unpreempted and the split is clean.

HF009 (analysis rule): raw ``time.perf_counter()``/``time.time()``
timing outside ``hfrep_tpu/obs/`` is banned — call sites route through
:func:`clock` / :func:`stopwatch` / :func:`timed` so measured wall time
stays inside the conservation plane.  All three work with telemetry
off (:func:`timed` still measures; it just accounts nothing).

Stdlib-only at import (the CLI stays instant); :class:`BlockTimer`
imports jax lazily, only when asked to sync.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from hfrep_tpu.obs import get_obs

#: every ledger category, in rendering order.  ``device_compute`` is
#: host time measurably blocked on the device (boundary syncs — on an
#: async backend that IS the un-overlapped device time the host waited
#: out); ``dispatch`` is un-blocked jitted-call host time (the attrib
#: window's measure; warmup windows' dispatch includes XLA compile);
#: ``checkpoint`` covers snapshot/checkpoint persistence, ``host_io``
#: every other instrumented host I/O, ``queue_wait`` backpressure and
#: empty-queue waits, ``obs_self`` the telemetry layer's own emit cost,
#: and ``unattributed`` the non-negative residual that closes the books.
CATEGORIES = ("device_compute", "dispatch", "host_io", "checkpoint",
              "queue_wait", "obs_self", "unattributed")

#: conservation tolerance: |Σ(cat) − wall| per window, as a fraction of
#: wall (plus an absolute 0.5 ms floor for micro-windows)
CONSERVATION_REL_TOL = 0.01
CONSERVATION_ABS_TOL_MS = 0.5

#: the ``--self-test`` gate's ceiling on ``timeline/obs_self_frac``
OBS_SELF_FRAC_MAX = 0.01


def clock() -> float:
    """The sanctioned monotonic wall-clock read (seconds; differences
    only).  HF009 bans raw ``time.perf_counter()`` outside ``obs/`` so
    every measured duration is at least *visible* to this plane; sites
    that can name a category should prefer :func:`timed`."""
    return time.perf_counter()


# ---------------------------------------------------------- accumulator
class _Frame:
    __slots__ = ("child",)

    def __init__(self):
        self.child = 0.0


class _Ledger:
    """Per-process category accumulator.  ``window`` holds seconds since
    the last flush; ``cum``/``cum_wall`` the whole-run totals behind the
    cumulative ``timeline/*`` gauges; the overlap pair accumulates over
    steady (non-warmup) windows only.  The lock guards totals (the serve
    layer flushes from worker threads); the frame stack is thread-local
    so concurrent drives cannot corrupt each other's nesting."""

    def __init__(self):
        self.lock = threading.Lock()
        self.window: Dict[str, float] = {}
        self.cum: Dict[str, float] = {}
        self.cum_wall = 0.0
        self.overlap_host = 0.0
        self.sync_wait = 0.0
        self._tls = threading.local()

    def frames(self) -> List[_Frame]:
        st = getattr(self._tls, "frames", None)
        if st is None:
            st = self._tls.frames = []
        return st

    def add(self, category: str, seconds: float) -> None:
        with self.lock:
            self.window[category] = self.window.get(category, 0.0) + seconds

    def take(self) -> Dict[str, float]:
        with self.lock:
            w, self.window = self.window, {}
            return w


_LEDGER = _Ledger()


def reset() -> None:
    """Drop all accumulated state (a fresh ``obs.enable`` arms a fresh
    run: the previous run's cumulative fractions must not bleed in)."""
    global _LEDGER
    _LEDGER = _Ledger()


def account(category: str, seconds: float) -> None:
    """Book ``seconds`` of already-measured wall time to ``category``.

    Inside an open :func:`timed` frame the time is *moved*, not
    duplicated: it is also added to the innermost frame's child total,
    so the enclosing category books only its exclusive remainder."""
    if seconds <= 0.0:
        return
    frames = _LEDGER.frames()
    if frames:
        frames[-1].child += seconds
    _LEDGER.add(category, seconds)


def note_obs_self(seconds: float) -> None:
    """``Obs._emit``'s self-measurement hook — the obs layer's own cost,
    booked like any other category so it shows up in (and is gated by)
    the same ledger it maintains."""
    account("obs_self", seconds)


def note_sync(seconds: float) -> None:
    """Host time spent blocked on the device at a boundary the drive
    already pays (``block_until_ready`` / the chunk ``device_get``) —
    the ``device_compute`` category's one source."""
    account("device_compute", seconds)


class stopwatch:
    """``with stopwatch() as sw: ...; sw.s`` — pure measurement, no
    ledger booking (phase timings that are reported, not accounted)."""

    s = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.s = time.perf_counter() - self._t0
        return False


class timed:
    """``with timed("checkpoint") as tm: ...; tm.s`` — measure AND book
    the block's EXCLUSIVE time to a category.  Nested ``timed`` blocks
    subtract cleanly (each frame books ``dur − child``), and
    :func:`account`/:func:`note_obs_self` calls inside the block move
    their seconds out of the enclosing frame the same way, so the
    window's Σ(categories) can never exceed the real elapsed wall by
    double counting.  Books nothing when ``category`` is falsy."""

    s = 0.0

    def __init__(self, category: Optional[str], **_attrs):
        self.category = category

    def __enter__(self):
        self._frame = _Frame()
        _LEDGER.frames().append(self._frame)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        self.s = dur
        frames = _LEDGER.frames()
        frames.pop()
        if self.category:
            _LEDGER.add(self.category, max(0.0, dur - self._frame.child))
            if frames:
                frames[-1].child += dur
        elif frames:
            # un-booked measurement: the child time already moved to
            # categories stays moved; only that portion leaves the parent
            frames[-1].child += self._frame.child
        return False


def flush_window(wall_s: float, *, drive: str, steps: Optional[int] = None,
                 warmup: bool = False, dispatch_s: Optional[float] = None,
                 sync_wait_s: Optional[float] = None, **attrs
                 ) -> Optional[dict]:
    """Close the ledger window against a synced wall clock.

    ``wall_s`` spans the window (ending at a boundary the drive already
    syncs at).  ``dispatch_s`` is the attrib window's un-blocked
    dispatch total for the same span (the caller flushes attrib first
    and hands the seconds over); ``sync_wait_s`` the measured host
    block at the boundary sync (→ ``device_compute``).  Emits ONE
    ``timeline_window`` event — Σ(``cat_ms``) == ``wall_ms`` exactly,
    oversums proportionally clamped and flagged — plus the cumulative
    ``timeline/*_frac`` gauges, and ``timeline/overlap_frac`` over
    steady windows: the fraction of boundary-relevant host time that
    overlapped device execution, ``(wall − sync) / wall`` (≈1 on a
    synchronous CPU backend where the dispatch IS the compute —
    structural only there; the TPU number is the ROADMAP item 2(a)
    baseline).  With telemetry off the window is discarded.  Never
    raises into a drive."""
    cats = _LEDGER.take()
    obs = get_obs()
    if not obs.enabled or not wall_s > 0:
        return None
    try:
        if dispatch_s:
            cats["dispatch"] = cats.get("dispatch", 0.0) + float(dispatch_s)
        if sync_wait_s:
            cats["device_compute"] = (cats.get("device_compute", 0.0)
                                      + float(sync_wait_s))
        measured = sum(cats.values())
        oversum = measured > wall_s * (1.0 + CONSERVATION_REL_TOL)
        if oversum and measured > 0:
            scale = wall_s / measured
            cats = {k: v * scale for k, v in cats.items()}
            measured = wall_s
        unattributed = max(0.0, wall_s - measured)
        cat_ms = {c: round(cats.get(c, 0.0) * 1e3, 3) for c in CATEGORIES
                  if c != "unattributed"}
        # close the books EXACTLY: the event's own numbers must satisfy
        # the invariant after rounding, so unattributed is the rounded
        # residual, not a rounded residual estimate
        wall_ms = round(wall_s * 1e3, 3)
        cat_ms["unattributed"] = max(
            0.0, round(wall_ms - sum(cat_ms.values()), 3))
        overlap = None
        if sync_wait_s is not None:
            overlap = max(0.0, wall_s - float(sync_wait_s)) / wall_s
        obs.event("timeline_window", drive=drive, wall_ms=wall_ms,
                  cat_ms=cat_ms, steps=steps, warmup=bool(warmup),
                  oversum=bool(oversum),
                  overlap_frac=(None if overlap is None
                                else round(overlap, 6)),
                  **attrs)
        with _LEDGER.lock:
            for c, v in cats.items():
                _LEDGER.cum[c] = _LEDGER.cum.get(c, 0.0) + v
            _LEDGER.cum["unattributed"] = (_LEDGER.cum.get("unattributed", 0.0)
                                           + unattributed)
            _LEDGER.cum_wall += wall_s
            if not warmup and sync_wait_s is not None:
                _LEDGER.overlap_host += max(0.0, wall_s - float(sync_wait_s))
                _LEDGER.sync_wait += float(sync_wait_s)
            cum, cum_wall = dict(_LEDGER.cum), _LEDGER.cum_wall
            o_host, o_sync = _LEDGER.overlap_host, _LEDGER.sync_wait
        for c in CATEGORIES:
            obs.gauge(f"timeline/{c}_frac").set(
                round(cum.get(c, 0.0) / cum_wall, 6), drive=drive)
        obs.gauge("timeline/wall_ms").set(round(cum_wall * 1e3, 3),
                                          drive=drive)
        if o_host + o_sync > 0:
            obs.gauge("timeline/overlap_frac").set(
                round(o_host / (o_host + o_sync), 6), drive=drive)
        return {"wall_ms": wall_ms, "cat_ms": cat_ms, "oversum": oversum,
                "overlap_frac": overlap}
    except Exception:       # telemetry must never kill a drive
        return None


# ----------------------------------------------------------- BlockTimer
class BlockTimer:
    """Device-synced step timing + the ledger's block boundary — the
    retired ``utils.profiling.StepTimer``'s contract (``block`` spans,
    ``step_time`` histogram, warmup-aware :attr:`steps_per_sec`, the
    attrib window flush) plus a :func:`flush_window` at the same synced
    boundary, with the boundary sync itself measured into
    ``device_compute`` and the steady windows feeding
    ``timeline/overlap_frac``.  Zero new syncs: the ``sync_on`` block
    was always the boundary's price."""

    def __init__(self, drive: str = "gan_block") -> None:
        self.drive = drive
        self.samples: List[tuple] = []      # (n_steps, secs, warmup)
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int, sync_on=None, warmup: bool = False) -> float:
        """Close one timing window.  ``warmup=True`` marks a sample that
        carries XLA compile (excluded from :attr:`steps_per_sec` when
        steady samples exist; its attrib window is discarded — that
        dispatch time IS the compile — but its ledger window still
        flushes, compile riding in ``dispatch``, so the run's wall
        stays conserved)."""
        sync_s = None
        if sync_on is not None:
            import jax
            t_sync = time.perf_counter()
            jax.block_until_ready(sync_on)
            sync_s = time.perf_counter() - t_sync
        dt = time.perf_counter() - self._t0
        self.samples.append((n_steps, dt, warmup))
        obs = get_obs()
        if obs.enabled:
            obs.record_span("block", dt, steps=int(n_steps),
                            warmup=bool(warmup), synced=sync_on is not None)
            if n_steps > 0:
                obs.histogram("step_time").observe(dt / n_steps,
                                                   warmup=bool(warmup))
            from hfrep_tpu.obs import attrib
            if warmup or sync_on is None:
                # compile-polluted or un-synced wall: either would lie
                # in the dispatch-vs-compute split
                with attrib._WINDOW.lock:
                    disp = sum(attrib._WINDOW.dispatch_s.values())
                attrib.reset_window()
                flush_window(dt, drive=self.drive, steps=int(n_steps),
                             warmup=True, dispatch_s=disp,
                             sync_wait_s=sync_s)
            else:
                out = attrib.flush_window(dt, steps=int(n_steps))
                flush_window(dt, drive=self.drive, steps=int(n_steps),
                             dispatch_s=((out or {}).get("dispatch_ms", 0.0)
                                         / 1e3),
                             sync_wait_s=sync_s)
        return dt

    @property
    def steps_per_sec(self) -> float:
        """Steady-state rate (warmup samples excluded when possible);
        ``nan`` on zero-duration windows rather than dividing by zero."""
        steady = [(n, t) for n, t, w in self.samples if not w]
        samples = steady or [(n, t) for n, t, _ in self.samples]
        steps = sum(n for n, _ in samples)
        secs = sum(t for _, t in samples)
        return steps / secs if secs > 0.0 else float("nan")

    def reset(self) -> None:
        self.samples.clear()


# ------------------------------------------------------- reconstruction
def _trace_records(run_dir) -> List[dict]:
    """The run's event records filtered to the verbatim-preserved set.

    The filter IS ``rollup.pin_record`` — the same predicate ``obs
    compact`` pins by — so the reconstruction consumes exactly the
    records that survive compaction whole, and its output is
    byte-identical on a compacted dir vs the raw original by
    construction (metric samples and plain spans, which compaction
    folds to aggregates, never enter the timeline)."""
    from hfrep_tpu.obs import report, rollup
    return [r for r in report.load_events(run_dir) if rollup.pin_record(r)]


def build_trace(run_dir, records: Optional[List[dict]] = None) -> str:
    """Chrome-trace/perfetto JSON (trace-event format) for one run dir.

    Spans become complete ("X") slices ending at their emit time,
    events become instants ("i"), ``timeline_window`` records
    additionally publish per-category counter ("C") tracks, and
    ``memory`` snapshots a high-water counter.  Deterministic
    serialization (sorted keys, fixed separators) so byte-equality is
    a meaningful check, not a formatting accident."""
    if records is None:
        records = _trace_records(run_dir)
    out: List[dict] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": f"hfrep run {Path(run_dir).name}"}},
    ]
    for rec in records:
        t_us = round(float(rec["t"]) * 1e6, 1)
        attrs = {k: v for k, v in rec.items()
                 if k not in ("v", "t", "type", "name", "dur", "depth")
                 and v is not None}
        if rec["type"] == "span":
            dur_us = round(float(rec["dur"]) * 1e6, 1)
            out.append({"ph": "X", "pid": 1,
                        "tid": 1 + int(rec.get("depth") or 0),
                        "name": str(rec["name"]),
                        "ts": round(t_us - dur_us, 1), "dur": dur_us,
                        "args": attrs})
        elif rec["type"] == "event":
            name = str(rec["name"])
            out.append({"ph": "i", "pid": 1, "tid": 0, "name": name,
                        "ts": t_us, "s": "p", "args": attrs})
            if name == "timeline_window" and isinstance(
                    rec.get("cat_ms"), dict):
                wall = rec.get("wall_ms")
                ts0 = (round(t_us - float(wall) * 1e3, 1)
                       if isinstance(wall, (int, float)) else t_us)
                out.append({"ph": "C", "pid": 1, "tid": 0,
                            "name": f"ledger:{rec.get('drive')}",
                            "ts": ts0, "args": {
                                c: rec["cat_ms"].get(c, 0.0)
                                for c in CATEGORIES}})
        elif rec["type"] == "memory":
            out.append({"ph": "C", "pid": 1, "tid": 0, "name": "memory",
                        "ts": t_us,
                        "args": {"high_water": rec.get("high_water")}})
    doc = {"traceEvents": out, "displayTimeUnit": "ms"}
    return json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n"


def ledger_from_events(records: List[dict]) -> dict:
    """Fold a run's ``timeline_window`` records into the whole-run
    ledger.  Run time the windows do not cover — instrumentation gaps,
    and the windows a SIGKILL's torn tail dropped — degrades into
    ``uncovered_ms`` and a larger effective ``unattributed``: the books
    still close, the verdict just says less.  Per-window conservation
    is re-checked (``max_residual_ms``) so a writer drifting from the
    invariant is caught at read time too."""
    windows = [r for r in records
               if r["type"] == "event" and r.get("name") == "timeline_window"
               and isinstance(r.get("cat_ms"), dict)]
    cats = {c: 0.0 for c in CATEGORIES}
    wall_ms = 0.0
    max_residual = 0.0
    oversums = 0
    o_host_ms = 0.0
    o_sync_ms = 0.0
    for w in windows:
        cm = w["cat_ms"]
        ww = float(w.get("wall_ms") or 0.0)
        wall_ms += ww
        total = 0.0
        for c in CATEGORIES:
            v = float(cm.get(c, 0.0) or 0.0)
            cats[c] += v
            total += v
        max_residual = max(max_residual, abs(total - ww))
        if w.get("oversum"):
            oversums += 1
        if not w.get("warmup") and isinstance(w.get("overlap_frac"),
                                              (int, float)):
            sync = max(0.0, ww * (1.0 - float(w["overlap_frac"])))
            o_sync_ms += sync
            o_host_ms += ww - sync
    ts = [float(r["t"]) for r in records]
    run_ms = (max(ts) - min(ts)) * 1e3 if ts else 0.0
    uncovered_ms = max(0.0, run_ms - wall_ms)
    denom = wall_ms + uncovered_ms
    fracs = {c: (cats[c] / denom if denom > 0 else 0.0) for c in CATEGORIES}
    fracs["unattributed"] = ((cats["unattributed"] + uncovered_ms) / denom
                             if denom > 0 else 0.0)
    return {
        "windows": len(windows),
        "wall_ms": round(wall_ms, 3),
        "run_span_ms": round(run_ms, 3),
        "uncovered_ms": round(uncovered_ms, 3),
        "categories_ms": {c: round(v, 3) for c, v in cats.items()},
        "fracs": {c: round(v, 6) for c, v in fracs.items()},
        "overlap_frac": (round(o_host_ms / (o_host_ms + o_sync_ms), 6)
                         if (o_host_ms + o_sync_ms) > 0 else None),
        "oversum_windows": oversums,
        "conservation": {
            "max_residual_ms": round(max_residual, 3),
            "ok": all(
                abs(sum(float(w["cat_ms"].get(c, 0.0) or 0.0)
                        for c in CATEGORIES) - float(w.get("wall_ms") or 0.0))
                <= max(CONSERVATION_ABS_TOL_MS,
                       float(w.get("wall_ms") or 0.0) * CONSERVATION_REL_TOL)
                for w in windows),
        },
    }


def render_ledger(doc: dict) -> str:
    lines = [f"timeline ledger — {doc['windows']} window(s), "
             f"{doc['wall_ms']:.1f} ms covered of "
             f"{doc['run_span_ms']:.1f} ms run span "
             f"({doc['uncovered_ms']:.1f} ms uncovered)"]
    for c in CATEGORIES:
        lines.append(f"  {c:16s} {doc['categories_ms'][c]:>12.1f} ms  "
                     f"{doc['fracs'][c] * 100:6.2f}%")
    ov = doc.get("overlap_frac")
    lines.append("  overlap_frac     "
                 + (f"{ov * 100:6.2f}%" if ov is not None else "     -"))
    cons = doc["conservation"]
    lines.append(f"  conservation     max residual {cons['max_residual_ms']}"
                 f" ms — {'OK' if cons['ok'] else 'VIOLATED'}"
                 + (f" ({doc['oversum_windows']} oversum window(s) clamped)"
                    if doc["oversum_windows"] else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------- CLI
def timeline_main(run_dir, out: Optional[str] = None,
                  fmt: str = "human") -> int:
    from hfrep_tpu.obs import report
    try:
        records = _trace_records(run_dir)
    except (OSError, report.SchemaError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if out:
        trace = build_trace(run_dir, records)
        tmp = Path(out).with_name(Path(out).name + ".tmp")
        tmp.write_text(trace)
        tmp.replace(out)
        print(f"wrote {out} ({len(trace)} bytes, "
              f"{len(records)} records)", file=sys.stderr)
    doc = ledger_from_events(records)
    if fmt == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(render_ledger(doc))
    return 0 if doc["conservation"]["ok"] else 1


# ------------------------------------------------------------ self-test
def fixture_dir() -> Path:
    """The committed timeline fixture: a run dir whose ledger was
    computed by hand (the numbers in :func:`self_test` are typed in,
    not derived), so writer and reader cannot drift together."""
    from hfrep_tpu.obs import report
    return report.fixture_dir() / "timeline"


def _expect(cond: bool, msg: str) -> None:
    if not cond:
        from hfrep_tpu.obs.report import SchemaError
        raise SchemaError(msg)


def self_test() -> int:
    """CI gate (tools/check.sh, env-stripped): the accumulator's
    conservation algebra, the hand-computed fixture ledger, the
    compaction byte-identity discipline, torn-tail degradation, and the
    ``obs_self_frac`` < 1% ceiling.  Pure-JSON stdout, diagnostics to
    stderr, 0/1."""
    import shutil
    import tempfile

    from hfrep_tpu.obs import report, rollup
    from hfrep_tpu.obs.report import SchemaError
    try:
        # -- accumulator algebra (no fixture, no jax): nested timed()
        # books exclusive time; account() inside a frame moves, never
        # duplicates; an un-booked stopwatch frame is transparent
        reset()
        with timed("host_io"):
            time.sleep(0.002)
            with timed("checkpoint"):
                time.sleep(0.002)
            account("queue_wait", 0.001)
        snap = dict(_LEDGER.window)
        total = sum(snap.values())
        _expect(snap.get("checkpoint", 0.0) > 0
                and snap.get("host_io", 0.0) > 0,
                f"nested categories missing: {snap}")
        _expect(snap["queue_wait"] == 0.001, "account() lost seconds")
        outer_wall = snap["host_io"] + snap["checkpoint"] + snap["queue_wait"]
        _expect(total <= outer_wall + 1e-9,
                f"nesting double-counted: {snap}")
        # oversum clamp: booked 3x the wall → flagged, Σ == wall exactly
        # (booked inside the session — enable() resets the ledger)
        from hfrep_tpu.obs import session
        with tempfile.TemporaryDirectory() as td:
            with session(Path(td) / "run", manifest=False,
                         compile_listener=False):
                account("host_io", 0.3)
                w = flush_window(0.1, drive="selftest", sync_wait_s=0.0)
            _expect(w is not None and w["oversum"],
                    f"oversum not flagged: {w}")
            _expect(abs(sum(w["cat_ms"].values()) - w["wall_ms"]) <= 0.01,
                    f"clamped window does not conserve: {w}")
            # the live window the session just wrote must satisfy the
            # invariant end to end through the writer+reader pair
            live = ledger_from_events(
                report.load_events(Path(td) / "run", strict=True))
            _expect(live["windows"] == 1 and live["conservation"]["ok"],
                    f"live round-trip failed: {live}")

        # -- the committed fixture, against HAND-COMPUTED numbers
        fx = fixture_dir()
        records = report.load_events(fx, strict=True)
        doc = ledger_from_events(records)
        # three 1000 ms windows (1 warmup + 2 steady); run spans
        # t=100.0→103.1 s, so 100 ms of the run is outside any window
        _expect(doc["windows"] == 3, f"fixture windows {doc['windows']}")
        _expect(doc["wall_ms"] == 3000.0, f"wall {doc['wall_ms']}")
        _expect(doc["run_span_ms"] == 3100.0 and doc["uncovered_ms"] == 100.0,
                f"span {doc['run_span_ms']} uncovered {doc['uncovered_ms']}")
        _expect(doc["categories_ms"]["device_compute"] == 1500.0,
                f"device_compute {doc['categories_ms']}")
        _expect(doc["categories_ms"]["dispatch"] == 1000.0,
                f"dispatch {doc['categories_ms']}")
        _expect(doc["categories_ms"]["checkpoint"] == 180.0,
                f"checkpoint {doc['categories_ms']}")
        _expect(doc["categories_ms"]["host_io"] == 100.0,
                f"host_io {doc['categories_ms']}")
        _expect(doc["categories_ms"]["queue_wait"] == 60.0,
                f"queue_wait {doc['categories_ms']}")
        _expect(doc["categories_ms"]["obs_self"] == 17.0,
                f"obs_self {doc['categories_ms']}")
        _expect(doc["categories_ms"]["unattributed"] == 143.0,
                f"unattributed {doc['categories_ms']}")
        _expect(doc["conservation"]["ok"] and doc["oversum_windows"] == 0,
                f"fixture conservation: {doc['conservation']}")
        # overlap over the two STEADY windows only: walls 1000+1000 ms
        # at overlap 0.3 and 0.4 → syncs 700+600, host 300+400 →
        # 700 / (700 + 1300)
        _expect(doc["overlap_frac"] == 0.35,
                f"overlap {doc['overlap_frac']}")
        obs_self_frac = doc["fracs"]["obs_self"]
        _expect(obs_self_frac < OBS_SELF_FRAC_MAX,
                f"obs_self_frac {obs_self_frac} >= {OBS_SELF_FRAC_MAX}")
        _expect(doc["fracs"]["unattributed"] < 0.10,
                f"unattributed_frac {doc['fracs']['unattributed']}")

        # -- compaction equivalence: rotate + compact a COPY, byte-equal
        raw = build_trace(fx)
        with tempfile.TemporaryDirectory() as td:
            # same basename as the fixture: compaction-in-place is the
            # claim under test, not the run dir's name (which the trace
            # embeds as its process_name)
            cp = Path(td) / fx.name
            shutil.copytree(fx, cp)
            rollup.compact(cp, force_rotate=True)
            compacted = build_trace(cp)
            _expect(compacted == raw,
                    "trace bytes differ on the compacted dir")
            # -- torn tail: SIGKILL mid-write drops the final window;
            # the ledger shrinks its covered set and grows unattributed
            tp = Path(td) / "torn"
            shutil.copytree(fx, tp)
            text = (tp / "events.jsonl").read_text()
            lines = text.splitlines(keepends=True)
            (tp / "events.jsonl").write_text(
                "".join(lines[:-2]) + lines[-2][: len(lines[-2]) // 2])
            torn_doc = ledger_from_events(report.load_events(tp))
            _expect(torn_doc["windows"] < doc["windows"],
                    "torn tail did not drop a window")
            _expect(torn_doc["conservation"]["ok"],
                    "torn ledger violates conservation")
            _expect(torn_doc["fracs"]["unattributed"]
                    >= doc["fracs"]["unattributed"],
                    "torn ledger did not degrade toward unattributed")
    except (OSError, json.JSONDecodeError, SchemaError, KeyError) as e:
        print(f"obs timeline self-test FAILED: {e}", file=sys.stderr)
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    finally:
        reset()
    print("obs timeline self-test OK", file=sys.stderr)
    print(json.dumps({
        "ok": True,
        "fixture": {"windows": doc["windows"], "wall_ms": doc["wall_ms"],
                    "obs_self_frac": obs_self_frac,
                    "unattributed_frac": doc["fracs"]["unattributed"],
                    "overlap_frac": doc["overlap_frac"]},
        "compaction_byte_identical": True,
        "torn_tail": {"windows": torn_doc["windows"],
                      "unattributed_frac":
                          torn_doc["fracs"]["unattributed"]},
    }))
    return 0
