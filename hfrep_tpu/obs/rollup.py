"""Durable incremental rollups: the fleet telemetry plane's fold layer.

A week-long soak (N serve replicas, actor pods, scenario daemons)
produces JSONL event streams that grow without bound and that the
post-mortem readers (``report``/``gate``/``explain``) were never meant
to re-parse continuously.  This module adds the missing tier between
the append-only streams and those readers:

* **Incremental ingest** (:func:`ingest`): an offset-cursor consumer in
  the ``_StreamFollower`` discipline — only newline-complete lines are
  ever consumed, so a torn tail is simply "not yet written" — that
  folds events into **time-bucketed rollup segments**: counters summed
  (running total kept, per-bucket increments from the ``delta`` attr),
  gauges folded last-wins with min/max envelopes, histograms merged
  through the same sparse log-bucket accumulator the in-process
  :class:`hfrep_tpu.obs.Histogram` uses.  The whole rollup state —
  segments AND cursors — is ONE atomically-replaced JSON document, so
  a SIGKILLed consumer either sees the pre-fold state (and re-folds the
  identical bytes) or the post-fold state (and skips them): exactly
  once, bit-identical on resume, idempotent on re-ingest.

* **Retention** (:func:`compact`, :func:`rotate_live`): an oversized
  live stream rotates aside to ``rollup/chunk-<n>.jsonl``; compaction
  folds each whole chunk into the rollup segments plus a *reader seed*
  (``rollup/compact.json``) and pins the low-volume evidence records
  verbatim (``rollup/pinned-<n>.jsonl`` — every ``event``/``memory``
  record, ``block``/``compile:*``/warmup/traced spans), then deletes
  the chunk.  ``report``/``gate``/``explain``/``--trace`` reconstruct
  their verdicts from seed + pinned + live and stay byte-equal to the
  raw-stream results (pinned by ``tests/test_rollup.py``); high-volume
  metric samples survive only as aggregates.  Compaction is driven by
  a per-chunk ledger inside ``compact.json``: fold → pin → publish
  ledger → unlink, each step idempotent, so a SIGKILL anywhere leaves
  a state the next run completes without losing or double-counting a
  single record.

Everything here is stdlib-only (no jax import): the fleet watcher and
the SLO evaluator run on hosts that never touch an accelerator.  The
one fault-injection surface is ``io_fail@rollup_publish`` — every
atomic publish (state, seed, pinned) crosses it, so the chaos subject
``rollup`` can kill or EIO the consumer mid-segment and mid-compaction.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from hfrep_tpu.obs import _HIST_BUCKETS_PER_DECADE
from hfrep_tpu.obs.report import EVENTS_NAME, parse_event

ROLLUP_DIR = "rollup"
STATE_NAME = "state.json"
COMPACT_NAME = "compact.json"
CHUNK_RE = re.compile(r"^chunk-(\d+)\.jsonl$")
PINNED_RE = re.compile(r"^pinned-(\d+)\.jsonl$")

STATE_VERSION = 1
DEFAULT_BUCKET_SECS = 60.0
#: default live-stream rotation threshold (``obs compact`` and the
#: writer-side ``Obs`` rotation share it)
DEFAULT_ROTATE_BYTES = 1 << 20

#: cursor identity: sha256 over the first ``min(_SIG_BYTES, offset)``
#: bytes at cursor-publish time.  Streams are append-only, so the head
#: window is immutable — the signature survives a rotation RENAME and
#: lets a cursor follow its stream to the new name instead of
#: re-consuming (double-count) or resetting (drop).
_SIG_BYTES = 4096

#: restart timestamps kept per run for storm detection (bounded)
_RESTART_TIMES_KEPT = 64


# ----------------------------------------------------------- publication
def _io_fault_hook():
    """The ``rollup_publish`` injection point (None when no plan armed;
    ImportError degrades to no-hook exactly like the obs sink's
    ``obs_append`` wiring)."""
    try:
        from hfrep_tpu.resilience import io_hook
    except ImportError:
        return None
    return io_hook("rollup_publish")


def _publish_bytes(path: Path, data: bytes) -> None:
    """Atomic durable publish: tmp + fsync + rename, behind the
    ``rollup_publish`` fault site."""
    path.parent.mkdir(parents=True, exist_ok=True)
    hook = _io_fault_hook()
    if hook is not None:
        hook()
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _canonical(obj) -> bytes:
    # NOT sort_keys: key order is first-seen fold order, which the
    # reader seed needs (gauge/counter dict order must reproduce the
    # raw stream's first-seen order for byte-equal verdicts)
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode()


def _load_json(path: Path) -> Optional[dict]:
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


# ------------------------------------------------------------ fold state
def _new_state(bucket_secs: float) -> dict:
    return {"v": STATE_VERSION, "bucket_secs": float(bucket_secs),
            "cursors": {}, "buckets": {}, "facts": _new_facts()}


def _new_facts() -> dict:
    return {"serve_drain": None,
            "breaker": {"opens": 0, "closes": 0, "state": "closed",
                        "last_t": None, "last_reason": None},
            "restarts": {"n": 0, "t": [], "actors": {}},
            "run_end": False}


def _new_bucket() -> dict:
    return {"counts": {}, "events": {}, "counters": {}, "gauges": {},
            "hists": {}, "spans": {}}


def new_hist() -> dict:
    return {"n": 0, "sum": 0.0, "min": None, "max": None,
            "zeros": 0, "negs": 0, "counts": {}}


def hist_observe(h: dict, v) -> None:
    """One sample into a serialized log-bucket accumulator — the same
    bucket math as :class:`hfrep_tpu.obs.Histogram` (keys stringified
    for JSON)."""
    try:
        v = float(v)
    except (TypeError, ValueError):
        return
    h["n"] += 1
    h["sum"] += v
    if h["min"] is None or v < h["min"]:
        h["min"] = v
    if h["max"] is None or v > h["max"]:
        h["max"] = v
    if v > 0.0 and math.isfinite(v):
        idx = str(math.floor(math.log10(v) * _HIST_BUCKETS_PER_DECADE))
        h["counts"][idx] = h["counts"].get(idx, 0) + 1
    elif v == 0.0:
        h["zeros"] += 1
    else:
        h["negs"] += 1


def hist_merge(dst: dict, src: dict) -> dict:
    """Fold ``src`` into ``dst`` (both serialized accumulators)."""
    dst["n"] += src["n"]
    dst["sum"] += src["sum"]
    for bound in ("min", "max"):
        v = src.get(bound)
        if v is not None:
            cur = dst.get(bound)
            keep = (cur is None or (v < cur if bound == "min" else v > cur))
            if keep:
                dst[bound] = v
    dst["zeros"] += src.get("zeros", 0)
    dst["negs"] += src.get("negs", 0)
    for idx, n in (src.get("counts") or {}).items():
        dst["counts"][idx] = dst["counts"].get(idx, 0) + int(n)
    return dst


def hist_percentile(h: dict, pct: float) -> Optional[float]:
    """Nearest-rank percentile of a serialized accumulator — the same
    definition as :meth:`hfrep_tpu.obs.Histogram.percentile` (geometric
    bucket midpoint, clamped to the observed [min, max])."""
    n = h["n"]
    if not n:
        return None
    rank = max(1, math.ceil(n * float(pct) / 100.0))
    acc = h.get("negs", 0)
    if rank <= acc:
        return h["min"]
    acc += h.get("zeros", 0)
    if rank <= acc:
        return 0.0
    for idx in sorted(int(k) for k in h["counts"]):
        acc += h["counts"][str(idx)]
        if rank <= acc:
            lo = 10.0 ** (idx / _HIST_BUCKETS_PER_DECADE)
            hi = 10.0 ** ((idx + 1) / _HIST_BUCKETS_PER_DECADE)
            rep = math.sqrt(lo * hi)
            return min(max(rep, h["min"]), h["max"])
    return h["max"]


def hist_cumulative(h: dict) -> List[Tuple[str, int]]:
    """Cumulative Prometheus buckets ``[(le, count), ..., ('+Inf', n)]``.

    ``le`` is each log-bucket's exact upper edge
    (``10**((idx+1)/100)``); zero and negative samples — which are ≤
    every positive edge — seed the running total so the exposition
    stays monotone."""
    out: List[Tuple[str, int]] = []
    acc = h.get("negs", 0) + h.get("zeros", 0)
    for idx in sorted(int(k) for k in (h.get("counts") or {})):
        acc += h["counts"][str(idx)]
        le = 10.0 ** ((idx + 1) / _HIST_BUCKETS_PER_DECADE)
        out.append((format(le, ".6g"), acc))
    out.append(("+Inf", h["n"]))
    return out


def _num(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f


def _fold_record(state: dict, rec: dict) -> None:
    """One parsed event into its time bucket (+ the fleet facts)."""
    bs = float(state["bucket_secs"]) or DEFAULT_BUCKET_SECS
    key = str(int(math.floor(float(rec["t"]) / bs)))
    bucket = state["buckets"].get(key)
    if bucket is None:
        bucket = state["buckets"][key] = _new_bucket()
    etype = rec["type"]
    bucket["counts"][etype] = bucket["counts"].get(etype, 0) + 1
    if etype == "span":
        name = str(rec["name"])
        s = bucket["spans"].setdefault(
            name, {"n": 0, "total_s": 0.0, "max_s": 0.0})
        dur = _num(rec.get("dur")) or 0.0
        s["n"] += 1
        s["total_s"] += dur
        if dur > s["max_s"]:
            s["max_s"] = dur
    elif etype == "metric":
        name, kind = str(rec["name"]), rec.get("kind")
        if kind == "counter":
            c = bucket["counters"].setdefault(
                name, {"last": 0, "inc": 0, "n": 0})
            c["last"] = rec["value"]
            d = _num(rec.get("delta"))
            if d is not None:
                c["inc"] += d
            c["n"] += 1
        elif kind == "gauge":
            g = bucket["gauges"].setdefault(
                name, {"last": None, "min": None, "max": None,
                       "sum": 0.0, "n": 0})
            g["last"] = rec["value"]
            v = _num(rec["value"])
            if v is not None:
                if g["min"] is None or v < g["min"]:
                    g["min"] = v
                if g["max"] is None or v > g["max"]:
                    g["max"] = v
                g["sum"] += v
            g["n"] += 1
        elif kind == "histogram":
            h = bucket["hists"].setdefault(name, new_hist())
            hist_observe(h, rec["value"])
    elif etype == "event":
        name = str(rec["name"])
        bucket["events"][name] = bucket["events"].get(name, 0) + 1
        _fold_fact(state["facts"], name, rec)


def _fold_fact(facts: dict, name: str, rec: dict) -> None:
    """The cross-replica invariant surface: the handful of lifecycle
    events the fleet watcher reasons about, folded to bounded facts."""
    if name == "serve_drain":
        facts["serve_drain"] = {
            "t": float(rec["t"]),
            "submitted": rec.get("submitted"),
            "terminal": rec.get("terminal"),
            "reason": rec.get("reason"),
            "flushed": rec.get("flushed")}
    elif name == "serve_breaker_open":
        b = facts["breaker"]
        b["opens"] += 1
        b["state"] = "open"
        b["last_t"] = float(rec["t"])
        b["last_reason"] = rec.get("reason")
    elif name == "serve_breaker_close":
        b = facts["breaker"]
        b["closes"] += 1
        b["state"] = "closed"
        b["last_t"] = float(rec["t"])
    elif name == "actor_restart":
        r = facts["restarts"]
        r["n"] += 1
        r["t"] = (r["t"] + [float(rec["t"])])[-_RESTART_TIMES_KEPT:]
        actor = str(rec.get("actor"))
        r["actors"][actor] = r["actors"].get(actor, 0) + 1
    elif name == "run_end":
        facts["run_end"] = True


def totals(state: dict) -> dict:
    """Whole-run fold of the bucketed segments (buckets in time order,
    so last-wins gauges and counter running totals resolve exactly as
    a single linear replay would)."""
    out = _new_bucket()
    for key in sorted(state["buckets"], key=int):
        b = state["buckets"][key]
        for etype, n in b["counts"].items():
            out["counts"][etype] = out["counts"].get(etype, 0) + n
        for name, n in b["events"].items():
            out["events"][name] = out["events"].get(name, 0) + n
        for name, c in b["counters"].items():
            dst = out["counters"].setdefault(
                name, {"last": 0, "inc": 0, "n": 0})
            dst["last"] = c["last"]
            dst["inc"] += c["inc"]
            dst["n"] += c["n"]
        for name, g in b["gauges"].items():
            dst = out["gauges"].setdefault(
                name, {"last": None, "min": None, "max": None,
                       "sum": 0.0, "n": 0})
            dst["last"] = g["last"]
            for bound, better in (("min", min), ("max", max)):
                if g[bound] is not None:
                    dst[bound] = (g[bound] if dst[bound] is None
                                  else better(dst[bound], g[bound]))
            dst["sum"] += g["sum"]
            dst["n"] += g["n"]
        for name, h in b["hists"].items():
            hist_merge(out["hists"].setdefault(name, new_hist()), h)
        for name, s in b["spans"].items():
            dst = out["spans"].setdefault(
                name, {"n": 0, "total_s": 0.0, "max_s": 0.0})
            dst["n"] += s["n"]
            dst["total_s"] += s["total_s"]
            dst["max_s"] = max(dst["max_s"], s["max_s"])
    return out


def n_records(state: dict) -> int:
    """Total event records folded into the segments."""
    return sum(n for b in state["buckets"].values()
               for n in b["counts"].values())


# --------------------------------------------------------------- cursors
def _sig_head(path: Path, sig_len: int) -> Tuple[str, int]:
    with open(path, "rb") as fh:
        data = fh.read(sig_len)
    return hashlib.sha256(data).hexdigest(), len(data)


def _sig_matches(path: Path, cur: dict) -> bool:
    sig_len = int(cur.get("sig_len") or 0)
    if sig_len == 0:
        # nothing consumed yet: identity is vacuous, any file matches
        return int(cur.get("offset") or 0) == 0
    try:
        sig, got = _sig_head(path, sig_len)
    except OSError:
        return False
    return got == sig_len and sig == cur.get("sig")


def _match_cursors(files: List[Path], cursors: Dict[str, dict],
                   ) -> Dict[str, dict]:
    """Pair each present stream with its durable cursor: by name where
    the head signature still matches, else by signature alone (a
    rotation RENAMED the stream; the cursor follows it), else a fresh
    cursor.  Never resets a matched offset — the no-double-count /
    no-drop core of resume."""
    matched: Dict[str, dict] = {}
    used = set()
    pending = []
    for f in files:
        cur = cursors.get(f.name)
        if cur is not None and _sig_matches(f, cur):
            matched[f.name] = dict(cur)
            used.add(f.name)
        else:
            pending.append(f)
    for f in pending:
        adopted = None
        for name, cur in cursors.items():
            if name in used or int(cur.get("sig_len") or 0) == 0:
                continue
            if _sig_matches(f, cur):
                adopted, _ = dict(cur), used.add(name)
                break
        matched[f.name] = adopted or {"offset": 0, "sig": "", "sig_len": 0}
    return matched


def _read_complete(path: Path, offset: int) -> Tuple[List[str], int]:
    """Newline-complete lines past ``offset`` (the shared torn-tail
    discipline: a torn tail is simply not consumed yet)."""
    with open(path, "rb") as fh:
        fh.seek(offset)
        data = fh.read()
    end = data.rfind(b"\n")
    if end < 0:
        return [], offset
    lines = data[:end + 1].decode("utf-8", errors="replace").splitlines()
    return lines, offset + end + 1


def _parse_line(line: str) -> Optional[dict]:
    """Follower discipline: a complete line that fails the schema is
    skipped (a foreign writer's debris must not wedge the consumer),
    exactly like the live tail's ``_StreamFollower``."""
    try:
        return parse_event(line, 0)
    except Exception:
        return None


# ---------------------------------------------------------------- layout
def rollup_dir(run_dir) -> Path:
    return Path(run_dir) / ROLLUP_DIR


def chunk_files(run_dir) -> List[Path]:
    ru = rollup_dir(run_dir)
    if not ru.is_dir():
        return []
    found = []
    for p in ru.iterdir():
        m = CHUNK_RE.match(p.name)
        if m:
            found.append((int(m.group(1)), p))
    return [p for _, p in sorted(found)]


def pinned_files(run_dir) -> List[Path]:
    ru = rollup_dir(run_dir)
    if not ru.is_dir():
        return []
    found = []
    for p in ru.iterdir():
        m = PINNED_RE.match(p.name)
        if m:
            found.append((int(m.group(1)), p))
    return [p for _, p in sorted(found)]


def next_chunk_index(run_dir) -> int:
    """First chunk number never used: neither on disk (chunk or pinned)
    nor in the compaction ledger — a compacted-and-deleted chunk's
    number must never be reissued."""
    taken = set()
    ru = rollup_dir(run_dir)
    if ru.is_dir():
        for p in ru.iterdir():
            m = CHUNK_RE.match(p.name) or PINNED_RE.match(p.name)
            if m:
                taken.add(int(m.group(1)))
    comp = _load_json(ru / COMPACT_NAME) or {}
    for name in (comp.get("chunks") or {}):
        m = CHUNK_RE.match(name)
        if m:
            taken.add(int(m.group(1)))
    n = 1
    while n in taken:
        n += 1
    return n


def _scan_streams(run_dir) -> List[Path]:
    """The streams the consumer follows, oldest first: rotation chunks
    in rotation order, then the live stream.  Previous-RUN rotations
    (``events-<n>.jsonl``) are deliberately excluded — the rollup, like
    ``load_events``, describes THIS run."""
    out = chunk_files(run_dir)
    live = Path(run_dir) / EVENTS_NAME
    if live.exists():
        out.append(live)
    return out


def load_state(run_dir) -> Optional[dict]:
    return _load_json(rollup_dir(run_dir) / STATE_NAME)


def load_compact(run_dir) -> Optional[dict]:
    return _load_json(rollup_dir(run_dir) / COMPACT_NAME)


# ----------------------------------------------------------------- ingest
def ingest(run_dir, *, bucket_secs: float = DEFAULT_BUCKET_SECS,
           persist: bool = True) -> Tuple[dict, int]:
    """Consume every complete line past the durable cursors and fold it
    into the bucketed segments; returns ``(state, records_consumed)``.

    ``persist=False`` folds in memory only (read-only evaluation over
    someone else's run dir — the SLO self-test must not dirty the
    committed fixture).  With ``persist=True`` the updated state —
    segments and advanced cursors in ONE document — is published
    atomically; a crash on either side of that publish re-folds or
    skips the same bytes, never half of them."""
    rd = Path(run_dir)
    state = load_state(rd) or _new_state(bucket_secs)
    files = _scan_streams(rd)
    cursors = _match_cursors(files, state.get("cursors") or {})
    consumed = 0
    for f in files:
        cur = cursors[f.name]
        try:
            lines, new_off = _read_complete(f, int(cur["offset"]))
        except OSError:
            continue
        for line in lines:
            rec = _parse_line(line)
            if rec is not None:
                _fold_record(state, rec)
                consumed += 1
        if new_off != cur["offset"]:
            cur["offset"] = new_off
            sig_len = min(_SIG_BYTES, new_off)
            try:
                cur["sig"], cur["sig_len"] = _sig_head(f, sig_len)
            except OSError:
                continue
    state["cursors"] = {f.name: cursors[f.name] for f in files}
    if persist:
        publish_state(rd, state)
    return state, consumed


def publish_state(run_dir, state: dict) -> None:
    path = rollup_dir(run_dir) / STATE_NAME
    data = _canonical(state)
    try:
        if path.read_bytes() == data:     # idempotent no-op re-ingest
            return
    except OSError:
        pass
    _publish_bytes(path, data)


# --------------------------------------------------------------- rotation
def rotate_live(run_dir, rotate_bytes: int = DEFAULT_ROTATE_BYTES, *,
                force: bool = False) -> Optional[Path]:
    """OFFLINE rotation: rename an oversized live stream to the next
    ``rollup/chunk-<n>.jsonl`` and leave a fresh empty live stream (the
    run dir keeps its shape contract).  The caller must know no writer
    holds the stream open — a live process rotates itself through
    ``Obs`` (writer-side rotation), which reopens its handle."""
    rd = Path(run_dir)
    live = rd / EVENTS_NAME
    try:
        size = live.stat().st_size
    except OSError:
        return None
    if size == 0 or (not force and size < rotate_bytes):
        return None
    ru = rollup_dir(rd)
    ru.mkdir(parents=True, exist_ok=True)
    dst = ru / f"chunk-{next_chunk_index(rd)}.jsonl"
    os.rename(live, dst)
    live.touch()
    return dst


# ------------------------------------------------------------- compaction
def _new_seed() -> dict:
    return {"counts": {}, "gauges": {}, "counters": {}, "hists": {},
            "spans": {}, "span_order": [], "type_order": []}


def pin_record(rec: dict) -> bool:
    """Verbatim-preservation rule: everything the post-mortem readers
    consume record-by-record.  ``event`` records (trace hops, program
    profiles, lifecycle facts, ``run_end``), ``memory`` snapshots, and
    the evidence-bearing spans (``block`` step timing, ``compile:*``
    digests, warmup windows, anything carrying a trace ID) stay whole;
    metric samples and plain spans survive as aggregates only."""
    etype = rec["type"]
    if etype in ("event", "memory"):
        return True
    if etype == "span":
        return bool(rec.get("warmup")
                    or rec["name"] == "block"
                    or str(rec["name"]).startswith("compile:")
                    or isinstance(rec.get("trace"), str)
                    or isinstance(rec.get("traces"), list))
    return False


def _fold_seed(seed: dict, rec: dict, pinned: bool) -> None:
    """Aggregate one compacted record into the reader seed.  Dict
    insertion order IS the contract: the readers re-derive first-seen
    order from it, which keeps their output byte-equal to a raw
    replay."""
    etype = rec["type"]
    if etype not in seed["type_order"]:
        # first-seen TYPE order across every compacted record, pinned
        # included: summarize's event_counts dict order must reproduce
        # the raw stream's
        seed["type_order"].append(etype)
    if etype == "span" and not rec.get("warmup"):
        name = str(rec["name"])
        if name not in seed["span_order"]:
            seed["span_order"].append(name)
    if pinned:
        return
    seed["counts"][etype] = seed["counts"].get(etype, 0) + 1
    if etype == "span":
        name = str(rec["name"])
        s = seed["spans"].setdefault(name, {"n": 0, "total_s": 0.0})
        s["n"] += 1
        s["total_s"] += _num(rec.get("dur")) or 0.0
    elif etype == "metric":
        name, kind = str(rec["name"]), rec.get("kind")
        if kind == "gauge":
            seed["gauges"][name] = rec["value"]
        elif kind == "counter":
            seed["counters"][name] = rec["value"]
        elif kind == "histogram":
            hist_observe(seed["hists"].setdefault(name, new_hist()),
                         rec["value"])


def compact(run_dir, *, bucket_secs: float = DEFAULT_BUCKET_SECS,
            rotate_bytes: Optional[int] = None,
            force_rotate: bool = False) -> dict:
    """Retention pass over one run dir: (optionally) rotate an
    oversized live stream, advance the durable ingest cursors over
    everything, then fold each whole rotation chunk into the reader
    seed + pinned evidence and delete it.

    Per-chunk protocol (each step idempotent, SIGKILL anywhere safe):

    1. fold the chunk against the *published* ledger (a re-run after a
       crash recomputes the identical merge — the source chunk cannot
       have changed);
    2. publish ``pinned-<n>.jsonl`` atomically (same bytes on retry);
    3. publish ``compact.json`` with the chunk entered in the ledger;
    4. unlink the chunk (a crash before this leaves a ledgered chunk
       the next pass merely unlinks).
    """
    rd = Path(run_dir)
    rotated = rotate_live(rd, rotate_bytes, force=force_rotate) \
        if (rotate_bytes is not None or force_rotate) else None
    state, consumed = ingest(rd, bucket_secs=bucket_secs, persist=True)
    ru = rollup_dir(rd)
    comp = load_compact(rd) or {"v": STATE_VERSION, "chunks": {},
                                "seed": _new_seed()}
    compacted = []
    for chunk in chunk_files(rd):
        if chunk.name in comp["chunks"]:
            chunk.unlink()          # crashed after ledger publish: finish
            compacted.append(chunk.name)
            continue
        try:
            lines, _ = _read_complete(chunk, 0)
        except OSError:
            continue
        pinned_lines: List[str] = []
        n_parsed = 0
        for line in lines:
            rec = _parse_line(line)
            if rec is None:
                continue
            n_parsed += 1
            pinned = pin_record(rec)
            _fold_seed(comp["seed"], rec, pinned)
            if pinned:
                pinned_lines.append(line)
        m = CHUNK_RE.match(chunk.name)
        idx = int(m.group(1)) if m else 0
        _publish_bytes(ru / f"pinned-{idx}.jsonl",
                       ("".join(ln + "\n" for ln in pinned_lines)).encode())
        comp["chunks"][chunk.name] = {"records": n_parsed,
                                      "pinned": len(pinned_lines)}
        _publish_bytes(ru / COMPACT_NAME, _canonical(comp))
        chunk.unlink()
        compacted.append(chunk.name)
    return {"rotated": str(rotated) if rotated else None,
            "ingested": consumed, "compacted": compacted,
            "chunks_total": len(comp["chunks"]),
            "records_compacted": sum(c["records"]
                                     for c in comp["chunks"].values())}


# ------------------------------------------------------------ reader seed
def summary_seed(run_dir) -> Optional[dict]:
    """What ``report.summarize`` must pre-load for a compacted run dir:
    the aggregate contribution of the records compaction folded away.
    None when the dir was never compacted (the raw path stays
    untouched)."""
    comp = load_compact(run_dir)
    if not comp or not comp.get("chunks"):
        return None
    seed = comp["seed"]
    return {"counts": dict(seed.get("counts") or {}),
            "type_order": list(seed.get("type_order") or []),
            "gauges": dict(seed.get("gauges") or {}),
            "counters": dict(seed.get("counters") or {}),
            "n_events": sum((seed.get("counts") or {}).values())}


def evidence_seed(run_dir) -> Optional[dict]:
    """What ``explain.run_evidence`` must pre-load: non-warmup span
    aggregates re-ordered to the raw stream's first-seen order (names
    whose records were all pinned get zero placeholders the pinned
    replay then fills), plus last-wins gauge/counter seeds."""
    comp = load_compact(run_dir)
    if not comp or not comp.get("chunks"):
        return None
    seed = comp["seed"]
    spans = {}
    agg = seed.get("spans") or {}
    for name in seed.get("span_order") or []:
        src = agg.get(name)
        spans[name] = ({"n": int(src["n"]), "total_s": float(src["total_s"])}
                       if src else {"n": 0, "total_s": 0.0})
    return {"spans": spans,
            "gauges": dict(seed.get("gauges") or {}),
            "counters": dict(seed.get("counters") or {})}


def disk_footprint(run_dir) -> int:
    """Bytes the telemetry plane holds on disk for one run dir: live
    stream + chunks + rollup artifacts (the bounded-retention soak
    asserts this stays ~flat while raw bytes written grow)."""
    rd = Path(run_dir)
    total = 0
    for p in [rd / EVENTS_NAME] + chunk_files(rd) + pinned_files(rd) + [
            rollup_dir(rd) / STATE_NAME, rollup_dir(rd) / COMPACT_NAME]:
        try:
            total += p.stat().st_size
        except OSError:
            pass
    return total
