"""Declarative SLOs with multi-window burn-rate alerts over the fleet.

The regression gate (:mod:`hfrep_tpu.obs.regress`) answers "did this
run get worse than its own history?"; an SLO answers the operator's
question: "is the fleet inside its error budget *right now*?"  This
module evaluates declarative objectives — p95 latency, shed rate,
error rate — over the time-bucketed rollup segments of every replica
under a fleet root, using the standard multi-window burn-rate scheme:

* **burn rate** = observed value / target.  Burn 1.0 consumes exactly
  the budget; burn 14 pages someone.
* **two windows** per objective: a *fast* window (the last few
  buckets — catches an active incident) and a *slow* window (a longer
  trailing range — rejects blips).  An alert **breaches** only when
  BOTH windows burn ≥ 1.0 (the classic Google SRE workbook reduction);
  fast-only burn is a *warning*.

Objectives are declarative JSON (``slo.json`` at the fleet root, or
``--slos FILE``), defaulting to :data:`DEFAULT_SLOS`:

* ``p95``   — nearest-rank p95 of a rollup histogram vs a target value
  (e.g. ``serve/latency_ms`` ≤ 250 ms);
* ``ratio`` — bad-events / (bad + good) vs a target fraction
  (e.g. shed rate ≤ 5%), counted from the bucketed ``event`` names.

Surfaced through ``obs slo`` (human table + ``--json`` doc + ``slo/*``
gauges for the history store) and ``obs gate --slo`` (exit 1 on any
breach, alongside the per-run regression verdict).  Stdlib-only.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Dict, List, Optional

from hfrep_tpu.obs import fleet, rollup

#: fast window = last N buckets, slow window = last M buckets (with the
#: default 60 s buckets: 5 min / 30 min — compressed-time fixtures pass
#: their own)
DEFAULT_FAST_BUCKETS = 5
DEFAULT_SLOW_BUCKETS = 30

SLO_FILE = "slo.json"

#: the serving tier's standing objectives (terminal-outcome event names
#: from serve/server.py; the latency histogram from the request path)
DEFAULT_SLOS: List[dict] = [
    {"name": "serve_latency_p95_ms", "kind": "p95",
     "hist": "serve/latency_ms", "target": 250.0},
    {"name": "serve_shed_rate", "kind": "ratio", "target": 0.05,
     "bad": ["serve_shed"],
     "good": ["serve_complete", "serve_degraded"]},
    {"name": "serve_error_rate", "kind": "ratio", "target": 0.01,
     "bad": ["serve_fault"],
     "good": ["serve_complete", "serve_degraded", "serve_shed"]},
]


def load_slos(path: Optional[str] = None,
              root: Optional[str] = None) -> List[dict]:
    """Objectives from ``--slos FILE``, else ``<root>/slo.json``, else
    the defaults.  Each entry needs ``name``/``kind``/``target``;
    malformed entries fail loud (a silently dropped SLO is an outage
    you stopped watching for)."""
    src = None
    if path is not None:
        src = Path(path)
    elif root is not None and (Path(root) / SLO_FILE).exists():
        src = Path(root) / SLO_FILE
    if src is None:
        return [dict(s) for s in DEFAULT_SLOS]
    slos = json.loads(src.read_text())
    if not isinstance(slos, list):
        raise ValueError(f"{src}: SLO file must be a JSON list")
    for s in slos:
        if not isinstance(s, dict):
            raise ValueError(f"{src}: SLO entries must be objects")
        missing = [k for k in ("name", "kind", "target") if k not in s]
        if missing:
            raise ValueError(f"{src}: SLO {s.get('name')!r} missing "
                             f"{missing}")
        if s["kind"] not in ("p95", "ratio"):
            raise ValueError(f"{src}: SLO {s['name']!r}: unknown kind "
                             f"{s['kind']!r}")
        if s["kind"] == "ratio" and "bad" not in s:
            raise ValueError(f"{src}: ratio SLO {s['name']!r} needs "
                             f"'bad' event names")
    return slos


# ---------------------------------------------------------------- windows
def _window(states: Dict[str, dict], n_buckets: int) -> dict:
    """Fleet-wide fold of each replica's last ``n_buckets`` time
    buckets: event counts summed, histograms merged.  Windows align
    per-replica (each replica's own trailing range) — replica clocks
    are process-relative, not wall-synchronized."""
    events: Dict[str, int] = {}
    hists: Dict[str, dict] = {}
    for state in states.values():
        keys = sorted(state.get("buckets") or {}, key=int)
        for key in keys[-int(n_buckets):]:
            b = state["buckets"][key]
            for name, n in b["events"].items():
                events[name] = events.get(name, 0) + n
            for name, h in b["hists"].items():
                rollup.hist_merge(hists.setdefault(name, rollup.new_hist()),
                                  h)
    return {"events": events, "hists": hists}


def _slo_value(slo: dict, window: dict) -> Optional[float]:
    """The objective's observed value over one window; None = no data
    (no data is *not* a breach — an idle fleet burns no budget)."""
    if slo["kind"] == "p95":
        h = window["hists"].get(slo.get("hist"))
        if not h or not h["n"]:
            return None
        return rollup.hist_percentile(h, 95.0)
    bad = sum(window["events"].get(n, 0) for n in slo.get("bad") or [])
    good = sum(window["events"].get(n, 0) for n in slo.get("good") or [])
    denom = bad + good
    if denom <= 0:
        return None
    return bad / denom


def evaluate(states: Dict[str, dict], slos: Optional[List[dict]] = None,
             *, fast_buckets: int = DEFAULT_FAST_BUCKETS,
             slow_buckets: int = DEFAULT_SLOW_BUCKETS) -> dict:
    """Multi-window burn rates for every objective over the fleet."""
    if slos is None:
        slos = [dict(s) for s in DEFAULT_SLOS]
    fast = _window(states, fast_buckets)
    slow = _window(states, slow_buckets)
    rows = []
    breaches = warnings = 0
    worst = 0.0
    for slo in slos:
        target = float(slo["target"])
        vf = _slo_value(slo, fast)
        vs = _slo_value(slo, slow)
        bf = (vf / target) if (vf is not None and target > 0) else None
        bs = (vs / target) if (vs is not None and target > 0) else None
        breach = bool(bf is not None and bs is not None
                      and bf >= 1.0 and bs >= 1.0)
        warn = bool(not breach and bf is not None and bf >= 1.0)
        breaches += breach
        warnings += warn
        for b in (bf, bs):
            if b is not None and b > worst:
                worst = b
        rows.append({
            "name": slo["name"], "kind": slo["kind"], "target": target,
            "fast": {"value": vf, "burn": bf, "buckets": int(fast_buckets)},
            "slow": {"value": vs, "burn": bs, "buckets": int(slow_buckets)},
            "breach": breach, "warning": warn,
            "no_data": vf is None and vs is None,
        })
    return {"v": 1, "slos": rows, "evaluated": len(rows),
            "breaches": breaches, "warnings": warnings,
            "worst_burn": worst, "ok": breaches == 0}


def render(result: dict) -> str:
    """Human table for ``obs slo``."""
    lines = [f"{'slo':<24} {'target':>10} {'fast':>10} {'slow':>10} "
             f"{'burn(f/s)':>12}  status"]

    def _fmt(v):
        return "-" if v is None else f"{v:.4g}"

    for row in result["slos"]:
        status = ("BREACH" if row["breach"]
                  else "warn" if row["warning"]
                  else "no-data" if row["no_data"] else "ok")
        burn = (f"{_fmt(row['fast']['burn'])}/"
                f"{_fmt(row['slow']['burn'])}")
        lines.append(f"{row['name']:<24} {row['target']:>10.4g} "
                     f"{_fmt(row['fast']['value']):>10} "
                     f"{_fmt(row['slow']['value']):>10} "
                     f"{burn:>12}  {status}")
    lines.append(f"=> {result['breaches']} breach(es), "
                 f"{result['warnings']} warning(s), worst burn "
                 f"{result['worst_burn']:.4g} over "
                 f"{result['evaluated']} objective(s)")
    return "\n".join(lines)


def emit_gauges(sink, result: dict) -> None:
    """``slo/*`` gauges into the ambient obs session (history-gated;
    every name has an explicit threshold row — burn-style gauges must
    not fall through to the inverted suffix fallback)."""
    sink.gauge("slo/evaluated").set(result["evaluated"])
    sink.gauge("slo/breaches").set(result["breaches"])
    sink.gauge("slo/warnings").set(result["warnings"])
    sink.gauge("slo/worst_burn").set(result["worst_burn"])


def evaluate_root(root, *, slos_path: Optional[str] = None,
                  fast_buckets: int = DEFAULT_FAST_BUCKETS,
                  slow_buckets: int = DEFAULT_SLOW_BUCKETS,
                  bucket_secs: float = rollup.DEFAULT_BUCKET_SECS,
                  persist: bool = False) -> dict:
    """One-call evaluation for the CLI: discover → ingest → evaluate,
    with the fleet invariant battery attached (an SLO report that hides
    a ledger deficit would be lying by omission)."""
    states = fleet.fleet_states(root, persist=persist,
                                bucket_secs=bucket_secs)
    slos = load_slos(slos_path, root=str(root))
    result = evaluate(states, slos, fast_buckets=fast_buckets,
                      slow_buckets=slow_buckets)
    result["fleet"] = fleet.invariants(states)
    result["root"] = str(root)
    return result


# -------------------------------------------------------------- self-test
def _fixture_root() -> Path:
    return Path(__file__).resolve().parent / "_fixture" / "fleet"


def self_test() -> int:
    """``obs slo --self-test``: evaluate the committed two-replica fleet
    fixture (read-only — the fixture stays pristine) and assert the
    planted defects are caught:

    * replica_b drained with ``terminal < submitted`` → the fleet
      ledger invariant must report the exact deficit;
    * a shed storm in the trailing buckets → the shed-rate SLO must
      breach on both burn windows;
    * the latency and error-rate objectives are healthy → must NOT
      breach (a self-test that only checks firing alarms would pass
      with an evaluator that breaches everything).

    Pure-JSON verdict on stdout, diagnostics on stderr, exit 0/1.
    """
    root = _fixture_root()
    checks: List[dict] = []

    def check(name: str, ok: bool, detail: str) -> None:
        checks.append({"name": name, "ok": bool(ok), "detail": detail})
        print(f"  {'ok' if ok else 'FAIL'}: {name} ({detail})",
              file=sys.stderr)

    print(f"slo self-test over {root}", file=sys.stderr)
    states = fleet.fleet_states(root, persist=False)
    check("fixture_replicas", len(states) == 2,
          f"discovered {sorted(states)}")
    inv = fleet.invariants(states)
    led = inv["ledger"]
    check("ledger_drop_caught",
          led["deficit"] == 2 and not led["ok"]
          and led["bad_replicas"] == ["replica_b"],
          f"submitted={led['submitted']} terminal={led['terminal']} "
          f"deficit={led['deficit']} bad={led['bad_replicas']}")
    check("ledger_sums",
          led["submitted"] == 74 and led["terminal"] == 72,
          f"{led['submitted']}→{led['terminal']}")

    result = evaluate(states, fast_buckets=2, slow_buckets=5)
    by_name = {r["name"]: r for r in result["slos"]}
    shed = by_name.get("serve_shed_rate") or {}
    check("shed_burn_breach",
          bool(shed.get("breach"))
          and (shed.get("fast") or {}).get("burn", 0) >= 1.0
          and (shed.get("slow") or {}).get("burn", 0) >= 1.0,
          f"fast={_j(shed, 'fast')} slow={_j(shed, 'slow')}")
    lat = by_name.get("serve_latency_p95_ms") or {}
    check("latency_healthy",
          not lat.get("breach") and not lat.get("no_data"),
          f"fast={_j(lat, 'fast')}")
    err = by_name.get("serve_error_rate") or {}
    check("error_rate_healthy",
          not err.get("breach") and not err.get("no_data"),
          f"fast={_j(err, 'fast')}")
    check("totals", result["breaches"] == 1 and not result["ok"],
          f"breaches={result['breaches']} worst={result['worst_burn']:.3g}")

    # read-only contract: evaluating a fixture must not dirty it
    dirty = [str(p) for p in root.rglob("rollup")]
    check("fixture_pristine", not dirty, f"rollup dirs: {dirty}")

    ok = all(c["ok"] for c in checks)
    doc = {"v": 1, "ok": ok, "checks": checks,
           "fleet": {"deficit": led["deficit"],
                     "bad_replicas": led["bad_replicas"]},
           "slo": {"breaches": result["breaches"],
                   "worst_burn": result["worst_burn"]}}
    print(json.dumps(doc, indent=2, sort_keys=True))
    print(f"slo self-test: {'OK' if ok else 'FAIL'} "
          f"({sum(c['ok'] for c in checks)}/{len(checks)})",
          file=sys.stderr)
    return 0 if ok else 1


def _j(row: dict, window: str) -> str:
    w = row.get(window) or {}
    v, b = w.get("value"), w.get("burn")
    return (f"{v:.4g}@burn={b:.3g}" if isinstance(v, float)
            and isinstance(b, float) else "no-data")
