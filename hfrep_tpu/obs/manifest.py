"""Run manifests: ``<run_dir>/run.json``.

One JSON document per run answering "what exactly produced these
events?" — git SHA (+dirty flag), jax/flax versions, host and device
inventory, and (merged in later by the trainer via ``Obs.annotate``) the
full experiment config and mesh shape.  The report CLI reads it to label
summaries and to recompute MFU from the model shape without re-running
anything.

Kept import-light: everything device-related is gated so the manifest
writer works (minus the device block) even where jax is absent or slow
to initialize.
"""

from __future__ import annotations

import dataclasses
import getpass
import json
import os
import platform
import subprocess
import sys
import time
from pathlib import Path
from typing import Optional

MANIFEST_NAME = "run.json"

#: manifest schema: v2 adds the optional ``traces`` list (xprof capture
#: links appended by :func:`add_trace_link` /
#: :func:`hfrep_tpu.obs.trace_capture`); readers accept v1 manifests
#: unchanged — every v1 field survives, ``traces`` is simply absent.
SCHEMA_VERSION = 2

#: keys :func:`write_manifest` always emits (the completeness test and
#: the report's self-test check against this list)
REQUIRED_KEYS = ("schema_version", "run_id", "created_unix", "created",
                 "git", "versions", "host", "devices", "argv")


def _git_info(cwd: Optional[str] = None) -> dict:
    def run(*args):
        try:
            out = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                                 text=True, timeout=10)
            return out.stdout.strip() if out.returncode == 0 else None
        except (OSError, subprocess.TimeoutExpired):
            return None

    sha = run("rev-parse", "HEAD")
    status = run("status", "--porcelain")
    return {"sha": sha,
            "dirty": bool(status) if status is not None else None,
            "branch": run("rev-parse", "--abbrev-ref", "HEAD")}


def _versions() -> dict:
    v = {"python": sys.version.split()[0]}
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        try:
            v[mod] = __import__(mod).__version__
        except Exception:
            v[mod] = None
    return v


def _devices() -> dict:
    try:
        import jax
        devs = jax.local_devices()
        return {"backend": jax.default_backend(),
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
                "local_device_count": len(devs),
                "global_device_count": jax.device_count(),
                "device_kind": devs[0].device_kind if devs else None}
    except Exception as e:           # manifest survives a broken backend
        return {"error": str(e)}


def _host() -> dict:
    try:
        user = getpass.getuser()
    except Exception:
        user = None
    return {"hostname": platform.node(), "platform": platform.platform(),
            "user": user, "pid": os.getpid(),
            "cwd": os.getcwd()}


def config_dict(cfg) -> dict:
    """An ``ExperimentConfig`` (or any dataclass / mapping) as plain data."""
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        return dataclasses.asdict(cfg)
    if isinstance(cfg, dict):
        return cfg
    return {"repr": repr(cfg)}


def write_manifest(run_dir, extra: Optional[dict] = None,
                   repo_root: Optional[str] = None) -> Path:
    """Write ``run.json``; returns its path.  ``extra`` merges at top
    level (used by :func:`hfrep_tpu.obs.enable` for caller context)."""
    run_dir = Path(run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    now = time.time()
    doc = {
        "schema_version": SCHEMA_VERSION,
        "run_id": run_dir.name,
        "created_unix": now,
        "created": time.strftime("%Y-%m-%dT%H:%M:%S%z", time.localtime(now)),
        "git": _git_info(repo_root or os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))),
        "versions": _versions(),
        "host": _host(),
        "devices": _devices(),
        "argv": list(sys.argv),
    }
    if extra:
        doc.update(extra)
    path = run_dir / MANIFEST_NAME
    _write_with_retry(path, json.dumps(doc, indent=2, default=str) + "\n")
    return path


def _write_with_retry(path: Path, text: str) -> None:
    """Manifest writes go through the bounded I/O retry policy (ISSUE 5):
    a flaky-storage blip must not take down ``enable()`` — nor go
    unrecorded (each retry is an ``io_retry`` event + counter).  The
    ``manifest`` fault-injection site lives inside the retried call."""
    from hfrep_tpu import resilience

    def _write():
        resilience.io_point("manifest")
        path.write_text(text)

    resilience.retry_io(_write, what="manifest")


def _update_manifest(run_dir, mutate) -> None:
    """Best-effort read-mutate-write of ``run.json`` (an empty doc when
    absent or corrupt, write failures swallowed): the one durability
    policy every post-hoc manifest writer shares — telemetry must never
    fail the run it describes."""
    path = Path(run_dir) / MANIFEST_NAME
    try:
        doc = json.loads(path.read_text()) if path.exists() else {}
    except (OSError, json.JSONDecodeError):
        doc = {}
    mutate(doc)
    try:
        _write_with_retry(path, json.dumps(doc, indent=2, default=str) + "\n")
    except OSError:
        pass


def annotate(run_dir, fields: dict) -> None:
    """Merge fields into an existing ``run.json`` (write one if absent —
    annotation must not be order-coupled to :func:`write_manifest`)."""
    _update_manifest(run_dir, lambda doc: doc.update(fields))


def add_program(run_dir, profile: dict) -> None:
    """Index one compiled-program fingerprint in the manifest's
    ``programs`` section (schema-additive, like ``traces``): a dict
    keyed by boundary name, each holding the list of distinct profiles
    (digest + cost/memory analysis) seen at that boundary — a SECOND
    entry appearing under one name during a run IS the silent-recompile
    signal ``obs explain`` diffs for.  Same-digest re-profiles dedup;
    best-effort like every post-hoc manifest write."""
    name = str(profile.get("name"))
    entry = {k: v for k, v in profile.items() if k != "name"}

    def mutate(doc):
        programs = doc.setdefault("programs", {})
        seen = programs.setdefault(name, [])
        digest = entry.get("hlo_sha256")
        if digest is not None and any(
                p.get("hlo_sha256") == digest for p in seen
                if isinstance(p, dict)):
            return
        seen.append(entry)

    _update_manifest(run_dir, mutate)


def add_trace_link(run_dir, trace_dir, **extra) -> None:
    """Append one xprof capture link to the manifest's ``traces`` list
    (schema v2) — best-effort like :func:`annotate`: linkage must never
    fail the profiled run."""
    _update_manifest(
        run_dir,
        lambda doc: doc.setdefault("traces", []).append(
            {"path": str(trace_dir), **extra}))


def read_manifest(run_dir) -> dict:
    path = Path(run_dir) / MANIFEST_NAME
    return json.loads(path.read_text())
