"""Analytic FLOPs/step + achieved-MFU accounting for the MTSS-WGAN-GP
train epoch (VERDICT r1 item 3; moved from ``tools/flops_accounting.py``
so the telemetry layer can compute per-step MFU in-process).

XLA's `compiled.cost_analysis()` reports ~3e7 flops/epoch for the (48,35)
step because `pallas_call` bodies are opaque to it — the LSTM kernels
hold nearly all the matmul FLOPs — so the accounting is analytic.

Model math per epoch (``GAN/MTSS_WGAN_GP.py:254-284`` semantics,
B=32, H=100, n_critic=5; matmul = 2mnk FLOPs):

* generator fwd on b samples:
  ``Gf(b) = 2bW(5HF + 12H²)``  — LSTM(F→H) proj 4HF + rec 4H²,
  LSTM(H→H) proj 4H² + rec 4H², Dense(H→F) HF.
* critic fwd on b: ``Cf(b) = 2bW(4HF + 12H² + H)`` — two LSTMs +
  Flatten→Dense(WH→1).
* per critic iteration: fake gen Gf(B) (stop-grad) + loss graph
  [Cf(2B) real⊕fake + Cf(B) interp + 2·Cf(B) GP input-grad] and its
  parameter backward ≈ 2× the loss graph (the GP second-order path is
  inside this 2× of a graph that already contains the inner backward):
  ≈ Gf(B) + 3·(Cf(2B) + 3·Cf(B)) = Gf(B) + 15·Cf(B).
* generator update: fwd Gf(B)+Cf(B), backward ≈ 2×: ≈ 3(Gf(B)+Cf(B)).
* epoch ≈ 8·Gf(B) + 78·Cf(B).

"Executed" FLOPs additionally count the lane padding the kernels run at
(H → Hp = 128 in every gate/recurrent matmul; output Dense stays
logical).  MFU is quoted against both the v5e bf16 peak (197 TFLOP/s)
and the f32-matmul peak (~½ of bf16); the workload's recurrent matmuls
are (32, Hp) × (Hp, 4Hp) — 32 of 128 systolic rows occupied — so the
practical ceiling is ~25% of peak before any other inefficiency.
"""

from __future__ import annotations

import sys

from hfrep_tpu.analysis.contracts import contract

PEAK_BF16 = 197e12          # TPU v5e (v5 lite) peak, bf16 matmul
PEAK_F32 = PEAK_BF16 / 2    # conventional f32-matmul rate on the MXU
B, H, HP, N_CRITIC = 32, 100, 128, 5


def gf(b, w, f, h):
    return 2 * b * w * (5 * h * f + 12 * h * h)


def cf(b, w, f, h):
    return 2 * b * w * (4 * h * f + 12 * h * h + h)


def epoch_flops(w, f, h=H, batch=B):
    """Logical FLOPs of one flagship training epoch at (window, features,
    hidden, per-model batch) — scalars in, scalar out."""
    return (8 * gf(batch, w, f, h) + 78 * cf(batch, w, f, h))


def mfu(steps_per_sec: float, w: int, f: int, h: int = H, batch: int = B,
        peak: float = PEAK_BF16) -> float:
    """Model FLOPs utilization of a measured epoch rate against ``peak``.

    Non-finite / non-positive rates (e.g. a BlockTimer with only warmup
    samples) come back as ``nan`` rather than raising inside telemetry.
    """
    try:
        rate = float(steps_per_sec)
    except (TypeError, ValueError):
        return float("nan")
    if not rate > 0.0 or rate != rate:
        return float("nan")
    return epoch_flops(w, f, h, batch) * rate / peak


@contract("(N,)->(N,)")
def mfu_series(step_times, w: int, f: int, h: int = H, batch: int = B,
               peak: float = PEAK_BF16):
    """Per-step MFU from an (N,) array of per-epoch seconds — the vector
    twin of :func:`mfu` for the report CLI's percentile columns."""
    import numpy as np
    dt = np.asarray(step_times, dtype=np.float64)
    flops = float(epoch_flops(w, f, h, batch))
    with np.errstate(divide="ignore", invalid="ignore"):
        out = np.where(dt > 0.0, flops / (dt * peak), np.nan)
    return out


def report(w, f, steps_per_sec):
    logical = epoch_flops(w, f, H)
    executed = epoch_flops(w, f, HP)    # H→Hp everywhere the kernels pad
    achieved = logical * steps_per_sec
    print(f"shape ({w}, {f}) @ {steps_per_sec} steps/s:")
    print(f"  model FLOPs/epoch:    {logical/1e9:.1f} GF  "
          f"(executed incl. lane padding: {executed/1e9:.1f} GF)")
    print(f"  achieved:             {achieved/1e12:.1f} TFLOP/s")
    print(f"  MFU vs bf16 peak:     {achieved/PEAK_BF16*100:.1f}%")
    print(f"  MFU vs f32 peak:      {achieved/PEAK_F32*100:.1f}%  "
          f"(batch occupies 32/128 MXU rows → ~25% practical ceiling)")


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    report(48, 35, float(argv[0]) if len(argv) > 0 else 553.0)
    report(168, 36, float(argv[1]) if len(argv) > 1 else 168.8)
    return 0


if __name__ == "__main__":
    sys.exit(main())
