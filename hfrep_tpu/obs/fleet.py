"""Fleet watcher: cross-replica invariants over a tree of run dirs.

ROADMAP items 3–4 (N serve replicas behind one admission tier, the
market-replay daemon) are long-lived *fleets*: many run dirs under one
root, each with its own event stream, whose core guarantees only mean
anything summed across replicas while they run.  This module discovers
every run dir under a fleet root (serve replicas, actor pods — whose
members already stream into ``<run>/actors/<name>`` — scenario
daemons), folds each through the durable rollup consumer
(:mod:`hfrep_tpu.obs.rollup`), and continuously evaluates:

* **ledger conservation** — fleet-wide ``terminal == submitted`` over
  every drained replica's authoritative ``serve_drain`` counts: a
  nonzero deficit is a silently dropped request *somewhere* in the
  fleet, the one invariant the whole serving tier is built around;
* **breaker state** — the per-replica circuit-breaker table
  (``serve_breaker_open``/``serve_breaker_close``), so "how many
  replicas are degraded right now" is one number;
* **restart storms** — ``actor_restart`` bursts (≥ *k* restarts inside
  one window) that per-run telemetry shows only as isolated events.

``obs export --fleet ROOT`` serves the whole thing as ONE federated
Prometheus exposition: every replica's rolled-up instruments labeled
``{replica="..."}`` plus the fleet-level ``hfrep_fleet_*`` gauges.
Stdlib-only, like the rest of the obs read path.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from hfrep_tpu.obs import rollup
from hfrep_tpu.obs.report import EVENTS_NAME
from hfrep_tpu.obs.tail import _prom_name

#: ≥ this many restarts inside one storm window = a restart storm
DEFAULT_STORM_RESTARTS = 3
DEFAULT_STORM_WINDOW_S = 60.0


def discover(root) -> List[Path]:
    """Every run dir under ``root``, recursively: any directory holding
    an ``events.jsonl`` (the same shape contract as multi-host proc
    dirs).  Actor member dirs (``<run>/actors/<name>``) qualify on
    their own — their streams are separate by design."""
    root = Path(root)
    if (root / EVENTS_NAME).exists():
        return [root]
    return sorted(p.parent for p in root.rglob(EVENTS_NAME))


def replica_label(root, run_dir) -> str:
    root, run_dir = Path(root), Path(run_dir)
    try:
        rel = run_dir.relative_to(root)
    except ValueError:
        return run_dir.name
    return str(rel) if str(rel) != "." else run_dir.name


def fleet_states(root, *, persist: bool = False,
                 bucket_secs: float = rollup.DEFAULT_BUCKET_SECS,
                 ) -> Dict[str, dict]:
    """label -> rolled-up state for every discovered replica.
    ``persist=True`` advances each replica's durable cursors (the
    continuous-watch mode); ``persist=False`` folds read-only (one-shot
    export, self-tests over committed fixtures)."""
    out: Dict[str, dict] = {}
    for run_dir in discover(root):
        state, _ = rollup.ingest(run_dir, bucket_secs=bucket_secs,
                                 persist=persist)
        out[replica_label(root, run_dir)] = state
    return out


def _storm(times: List[float], restarts: int, window_s: float) -> bool:
    if len(times) < restarts:
        return False
    times = sorted(times)
    return any(times[i + restarts - 1] - times[i] <= window_s
               for i in range(len(times) - restarts + 1))


def invariants(states: Dict[str, dict], *,
               storm_restarts: int = DEFAULT_STORM_RESTARTS,
               storm_window_s: float = DEFAULT_STORM_WINDOW_S) -> dict:
    """The cross-replica invariant battery over rolled-up states."""
    submitted = terminal = 0
    drained, pending, bad_replicas = [], [], []
    breaker_table: Dict[str, dict] = {}
    restarts_total = 0
    storms: List[str] = []
    by_replica: Dict[str, int] = {}
    for label in sorted(states):
        facts = states[label].get("facts") or rollup._new_facts()
        drain = facts.get("serve_drain")
        if drain is not None:
            s, t = rollup._num(drain.get("submitted")), \
                rollup._num(drain.get("terminal"))
            if s is not None and t is not None:
                drained.append(label)
                submitted += int(s)
                terminal += int(t)
                if int(s) != int(t):
                    bad_replicas.append(label)
        elif (facts.get("breaker", {}).get("opens")
              or _has_serve_traffic(states[label])):
            # a serve replica that never drained: ledger still open
            pending.append(label)
        b = facts.get("breaker") or {}
        if b.get("opens") or b.get("closes"):
            breaker_table[label] = {
                "state": b.get("state"), "opens": b.get("opens"),
                "closes": b.get("closes"),
                "last_reason": b.get("last_reason")}
        r = facts.get("restarts") or {}
        n = int(r.get("n") or 0)
        if n:
            restarts_total += n
            by_replica[label] = n
            if _storm(list(r.get("t") or []), storm_restarts,
                      storm_window_s):
                storms.append(label)
    deficit = submitted - terminal
    ledger_ok = deficit == 0 and not bad_replicas
    return {
        "v": 1,
        "replicas": len(states),
        "ledger": {"drained": len(drained), "pending": len(pending),
                   "submitted": submitted, "terminal": terminal,
                   "deficit": deficit, "bad_replicas": bad_replicas,
                   "ok": ledger_ok},
        "breakers": {
            "open": sum(1 for b in breaker_table.values()
                        if b["state"] == "open"),
            "table": breaker_table},
        "restarts": {"total": restarts_total, "storms": storms,
                     "by_replica": by_replica},
        "ok": ledger_ok and not storms,
    }


def _has_serve_traffic(state: dict) -> bool:
    tot = rollup.totals(state)
    if "serve/latency_ms" in tot["hists"]:
        return True
    return any(name.startswith("serve_") for name in tot["events"])


# ------------------------------------------------------------- exposition
def _esc(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(states: Dict[str, dict],
                    inv: Optional[dict] = None) -> str:
    """One federated exposition: every replica's rollup totals labeled
    ``{replica="..."}``, then the fleet-level invariant gauges."""
    if inv is None:
        inv = invariants(states)
    gauges: Dict[str, List] = {}
    counters: Dict[str, List] = {}
    hists: Dict[str, List] = {}
    for label in sorted(states):
        tot = rollup.totals(states[label])
        for k, g in tot["gauges"].items():
            v = rollup._num(g.get("last"))
            if v is not None:
                gauges.setdefault(k, []).append((label, v))
        for k, c in tot["counters"].items():
            v = rollup._num(c.get("last"))
            if v is not None:
                counters.setdefault(k, []).append((label, v))
        for k, h in tot["hists"].items():
            hists.setdefault(k, []).append((label, h))
    lines = []
    for name in sorted(gauges):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} gauge")
        for label, v in gauges[name]:
            lines.append(f'{pname}{{replica="{_esc(label)}"}} {v}')
    for name in sorted(counters):
        pname = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pname} counter")
        for label, v in counters[name]:
            lines.append(f'{pname}{{replica="{_esc(label)}"}} {v}')
    for name in sorted(hists):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for label, h in hists[name]:
            for le, cum in rollup.hist_cumulative(h):
                lines.append(f'{pname}_bucket{{replica="{_esc(label)}",'
                             f'le="{le}"}} {cum}')
            lines.append(f'{pname}_count{{replica="{_esc(label)}"}} '
                         f'{h["n"]}')
            lines.append(f'{pname}_sum{{replica="{_esc(label)}"}} '
                         f'{h["sum"]}')
    fleet_gauges = [
        ("hfrep_fleet_replicas", inv["replicas"]),
        ("hfrep_fleet_submitted", inv["ledger"]["submitted"]),
        ("hfrep_fleet_terminal", inv["ledger"]["terminal"]),
        ("hfrep_fleet_ledger_deficit", inv["ledger"]["deficit"]),
        ("hfrep_fleet_ledger_pending", inv["ledger"]["pending"]),
        ("hfrep_fleet_breakers_open", inv["breakers"]["open"]),
        ("hfrep_fleet_restarts", inv["restarts"]["total"]),
        ("hfrep_fleet_restart_storms", len(inv["restarts"]["storms"])),
    ]
    for pname, v in fleet_gauges:
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {v}")
    return "\n".join(lines) + "\n"


def emit_gauges(sink, inv: dict) -> None:
    """Narrate one watch pass into the ambient obs session (``fleet/*``
    gauges ride the history store through the regression gate — every
    name here has an explicit ``regress.DEFAULT_THRESHOLDS`` row)."""
    sink.gauge("fleet/replicas").set(inv["replicas"])
    sink.gauge("fleet/submitted").set(inv["ledger"]["submitted"])
    sink.gauge("fleet/terminal").set(inv["ledger"]["terminal"])
    sink.gauge("fleet/ledger_deficit").set(inv["ledger"]["deficit"])
    sink.gauge("fleet/breakers_open").set(inv["breakers"]["open"])
    sink.gauge("fleet/restarts").set(inv["restarts"]["total"])
    sink.gauge("fleet/restart_storms").set(len(inv["restarts"]["storms"]))


def watch(root, *, interval: float = 5.0,
          iterations: Optional[int] = None, out: Optional[str] = None,
          bucket_secs: float = rollup.DEFAULT_BUCKET_SECS,
          persist: bool = True, sink=None) -> dict:
    """The continuous mode: ingest → invariants → exposition, forever
    (or ``iterations`` passes).  ``out`` atomically republishes the
    exposition each pass (a node-exporter-style textfile target)."""
    inv: dict = {}
    passes = 0
    while True:
        states = fleet_states(root, persist=persist,
                              bucket_secs=bucket_secs)
        inv = invariants(states)
        if sink is not None:
            emit_gauges(sink, inv)
        text = prometheus_text(states, inv)
        if out is not None:
            rollup._publish_bytes(Path(out), text.encode())
        print(f"fleet {root}: {inv['replicas']} replicas, ledger "
              f"{inv['ledger']['submitted']}→{inv['ledger']['terminal']} "
              f"(deficit {inv['ledger']['deficit']}), "
              f"{inv['breakers']['open']} breaker(s) open, "
              f"{len(inv['restarts']['storms'])} storm(s)",
              file=sys.stderr)
        passes += 1
        if iterations is not None and passes >= iterations:
            return inv
        try:
            time.sleep(max(0.05, float(interval)))
        except KeyboardInterrupt:
            return inv


def export_fleet_main(root, *, out: Optional[str] = None,
                      watch_iterations: Optional[int] = None,
                      interval: float = 5.0,
                      persist: bool = False) -> int:
    """``obs export --fleet ROOT`` entry: one-shot federated exposition
    (or a bounded watch loop with ``--watch N``)."""
    if not discover(root):
        print(f"no {EVENTS_NAME} under {root}", file=sys.stderr)
        return 1
    if watch_iterations is not None:
        watch(root, interval=interval, iterations=watch_iterations,
              out=out, persist=persist)
        return 0
    states = fleet_states(root, persist=persist)
    inv = invariants(states)
    text = prometheus_text(states, inv)
    if out is None:
        sys.stdout.write(text)
    else:
        rollup._publish_bytes(Path(out), text.encode())
    return 0


def fleet_json(root, *, persist: bool = False) -> dict:
    """The invariant battery as one JSON doc (the ``obs slo`` CLI and
    the self-test embed it)."""
    states = fleet_states(root, persist=persist)
    return dict(invariants(states), root=str(root))
