"""Regression engine: robust rolling baselines over the run history.

Given one run (a history record, ingested or not) and the history index
(:mod:`hfrep_tpu.obs.history`), decide per metric whether the run
regressed against the rolling baseline of *comparable* runs — same
``(family, shape, mesh, host, backend)`` key — and produce a machine- and
human-readable verdict.  This is the consumer the telemetry layer was
missing: Podracer-style continuous throughput/MFU accounting
(arXiv:2104.06272) needs something that remembers, not just reports.

Baseline math — median/MAD, not mean/stddev: bench series carry
occasional far outliers (a compile-heavy warmstart, a noisy-neighbor
session) that would poison a mean baseline and inflate a stddev gate
into uselessness.  The baseline is the **median** of the last
``window`` comparable samples; the allowed deviation is

    max(rel_tol * |median|,  mad_mult * 1.4826 * MAD,  abs_tol)

— the relative-tolerance floor keeps a zero-MAD series (N identical
CPU-fixture numbers) from flagging measurement jitter, the scaled MAD
term (1.4826 ≈ consistency with σ under normality) adapts to genuinely
noisy series, and ``abs_tol`` covers integer metrics like compile
counts where ±1 is noise at any scale.

Small-N behavior: fewer than ``min_runs`` comparable samples yields an
``insufficient-history`` check that PASSES — a gate must not brick the
first CI run on a new host/mesh; it starts enforcing once the series
exists.  A run that measured *nothing at all* (every check ``missing``:
empty event stream, writer killed before the first flush) fails as
``no-data`` — a green gate with zero evidence would be the silently
disarmed sentinel.  Direction matters: steps/sec and MFU regress
*down*, step times, memory and compile counts regress *up*;
improvements never fail.

Stdlib-only, like the rest of the obs read path.
"""

from __future__ import annotations

import json
from statistics import median
from typing import Dict, List, Optional

from hfrep_tpu.obs.history import _num

#: metric -> gate config.  ``direction``: "up" = higher is better.
#: ``rel_tol`` is the relative floor on the allowed deviation, ``abs_tol``
#: an absolute floor (integer-ish metrics), ``mad_mult`` scales the
#: robust spread term.  Every threshold is overridable per metric via
#: the CLI / function arguments; unlisted metrics (e.g. ``bench/...``
#: gauges) gate with :data:`DEFAULT_RULE` and direction "up".
DEFAULT_THRESHOLDS: Dict[str, dict] = {
    "steps_per_sec":           {"direction": "up",   "rel_tol": 0.05,
                                "mad_mult": 5.0},
    "step_time_p50_s":         {"direction": "down", "rel_tol": 0.08,
                                "mad_mult": 5.0},
    "step_time_p95_s":         {"direction": "down", "rel_tol": 0.15,
                                "mad_mult": 5.0},
    "mfu":                     {"direction": "up",   "rel_tol": 0.05,
                                "mad_mult": 5.0},
    "memory_high_water_bytes": {"direction": "down", "rel_tol": 0.10,
                                "mad_mult": 5.0},
    "backend_compiles":        {"direction": "down", "rel_tol": 0.0,
                                "abs_tol": 2.0, "mad_mult": 5.0},
    "compile_secs":            {"direction": "down", "rel_tol": 0.50,
                                "mad_mult": 5.0},
    # bench_extra.py's lower-is-better emissions (epoch time, divergence
    # from the reference distribution) — without these the fallback rule
    # would invert their gates
    "bench/ae_epoch_time_ms":  {"direction": "down", "rel_tol": 0.10,
                                "mad_mult": 5.0},
    "bench/js_div_regenerated": {"direction": "down", "rel_tol": 0.25,
                                 "mad_mult": 5.0},
    # bench.py's headline gauges (ISSUE 11 / HF001: every statically-named
    # bench/serve/scenario gauge carries an explicit entry — the suffix
    # heuristic guessed these right, but "right by heuristic" is exactly
    # the class that folded serve/shed_rate and scenario/pad_waste_frac
    # inverted; rates regress down = direction "up")
    "bench/headline_steps_per_sec":     {"direction": "up", "rel_tol": 0.05,
                                         "mad_mult": 5.0},
    "bench/headline_f32_steps_per_sec": {"direction": "up", "rel_tol": 0.05,
                                         "mad_mult": 5.0},
    "bench/prod_168x36_steps_per_sec":  {"direction": "up", "rel_tol": 0.05,
                                         "mad_mult": 5.0},
    "bench/dp_shard_map_steps_per_sec": {"direction": "up", "rel_tol": 0.08,
                                         "mad_mult": 5.0},
    "bench/sp_prod_steps_per_sec":      {"direction": "up", "rel_tol": 0.08,
                                         "mad_mult": 5.0},
    "bench/bf16_headline_speedup":      {"direction": "up", "rel_tol": 0.05,
                                         "mad_mult": 5.0},
    # structural marker (ISSUE 15): 1.0 while the dp/sp probes launch
    # through the unified partition-rule mesh path — identical run to
    # run, so the absolute floor flags a run that REPORTS a lower
    # value.  (A rollback that stops emitting the gauge reads as
    # not-measured and passes — missing metrics are never failures by
    # design; the committed series diff is the absence tripwire.)
    "bench/mesh_unified":               {"direction": "up", "rel_tol": 0.0,
                                         "abs_tol": 0.5, "mad_mult": 0.0},
    # tools/bench_ae.py (chunked early-exit + multi-dataset fabric)
    "bench/ae_chunk_speedup":   {"direction": "up",   "rel_tol": 0.15,
                                 "mad_mult": 5.0},
    "bench/ae_full_scan_s":     {"direction": "down", "rel_tol": 0.15,
                                 "mad_mult": 5.0},
    "bench/ae_chunked_exit_s":  {"direction": "down", "rel_tol": 0.15,
                                 "mad_mult": 5.0},
    "bench/ae_epochs_per_sec":  {"direction": "up",   "rel_tol": 0.10,
                                 "mad_mult": 5.0},
    "bench/ae_multi_batched_s": {"direction": "down", "rel_tol": 0.15,
                                 "mad_mult": 5.0},
    "bench/ae_multi_serial_s":  {"direction": "down", "rel_tol": 0.15,
                                 "mad_mult": 5.0},
    "bench/ae_multi_speedup":   {"direction": "up",   "rel_tol": 0.15,
                                 "mad_mult": 5.0},
    # tools/bench_async.py (actor-fabric overlap probe)
    "bench/async_overlap_speedup": {"direction": "up",   "rel_tol": 0.15,
                                    "mad_mult": 5.0},
    "bench/async_sequential_s":    {"direction": "down", "rel_tol": 0.15,
                                    "mad_mult": 5.0},
    "bench/async_overlapped_s":    {"direction": "down", "rel_tol": 0.15,
                                    "mad_mult": 5.0},
    # tools/bench_overlap.py (async boundary engine; ISSUE 19): the
    # steady-window overlap fraction at the two instrumented drive
    # boundaries (chunked-AE chunk stops, GAN block stops), re-emitted
    # under bench/ so the probe's own series gates by name.  Fractions
    # in [0,1] near saturation — a relative tolerance is ~nothing, so
    # the gate is the same abs-0.10 floor the timeline/* gauges use.
    "bench/overlap_gan_block":     {"direction": "up", "rel_tol": 0.0,
                                    "abs_tol": 0.10, "mad_mult": 5.0},
    "bench/overlap_ae_chunk":      {"direction": "up", "rel_tol": 0.0,
                                    "abs_tol": 0.10, "mad_mult": 5.0},
    # serving-layer gauges (tools/bench_serve.py; ISSUE 8).  These rules
    # also decide the cross-host gauge FOLD direction in
    # history.fold_gauges (min where higher-better / max for costs), so
    # the serve/* vocabulary must be explicit here: ``serve/shed_rate``
    # in particular would hit the ``_rate`` = higher-is-better suffix
    # heuristic and gate (and fold) inverted.  shed_rate/queue_depth use
    # absolute floors — both sit near 0 on a healthy run, where a
    # relative tolerance of ~nothing would flag scheduler jitter.
    "serve/qps":               {"direction": "up",   "rel_tol": 0.10,
                                "mad_mult": 5.0},
    "serve/p50_ms":            {"direction": "down", "rel_tol": 0.15,
                                "mad_mult": 5.0},
    "serve/p95_ms":            {"direction": "down", "rel_tol": 0.25,
                                "mad_mult": 5.0},
    "serve/shed_rate":         {"direction": "down", "rel_tol": 0.0,
                                "abs_tol": 0.05, "mad_mult": 5.0},
    "serve/queue_depth":       {"direction": "down", "rel_tol": 0.0,
                                "abs_tol": 4.0, "mad_mult": 5.0},
    # serve/compiles is a counter (it never rides into the history store,
    # which indexes gauges only) but it still cross-host FOLDS through
    # fold_gauges' direction lookup if a future summary carries it, and
    # HF001 requires the explicit row: compile counts are costs, ±2 noise
    "serve/compiles":          {"direction": "down", "rel_tol": 0.0,
                                "abs_tol": 2.0, "mad_mult": 5.0},
    # scenario-factory gauges (tools/bench_scenario.py; ISSUE 9).  Every
    # entry is explicit — the ``shed_rate`` lesson: ``pad_waste_frac``
    # has no cost suffix and would gate (and cross-host fold) INVERTED
    # under the higher-is-better fallback.  ``lanes`` is structural (the
    # fused program's window×latent grid): identical run to run at a
    # fixed key, so a 0.5 absolute floor flags any silent shrink while
    # config changes re-key the series anyway.  ``pad_waste_frac`` sits
    # near 0 on a healthy schedule — absolute floor, not relative.
    "scenario/windows_per_sec": {"direction": "up",   "rel_tol": 0.10,
                                 "mad_mult": 5.0},
    "scenario/lanes":           {"direction": "up",   "rel_tol": 0.0,
                                 "abs_tol": 0.5, "mad_mult": 0.0},
    "scenario/pad_waste_frac":  {"direction": "down", "rel_tol": 0.0,
                                 "abs_tol": 0.05, "mad_mult": 5.0},
    "scenario/bank_windows_per_sec": {"direction": "up", "rel_tol": 0.15,
                                      "mad_mult": 5.0},
    # flight-recorder health gauges (hfrep_tpu/obs/health.py; ISSUE 12).
    # Diagnostics, not perf: directions matter mainly for the cross-host
    # FOLD (a pod reports its WORST member's health), so norms are
    # "down" (a growing grad/update norm is instability) with generous
    # relative floors — the NaN tripwire, not the gate, is the alarm —
    # and the nonfinite counts use absolute floors (any value > 0 has
    # already fired a ``numeric_fault`` event; gating re-litigates it).
    "health/g_grad_norm":   {"direction": "down", "rel_tol": 1.0,
                             "mad_mult": 5.0},
    "health/d_grad_norm":   {"direction": "down", "rel_tol": 1.0,
                             "mad_mult": 5.0},
    "health/update_norm":   {"direction": "down", "rel_tol": 1.0,
                             "mad_mult": 5.0},
    "health/param_norm":    {"direction": "down", "rel_tol": 1.0,
                             "mad_mult": 5.0},
    "health/nonfinite":     {"direction": "down", "rel_tol": 0.0,
                             "abs_tol": 0.5, "mad_mult": 0.0},
    "health/ae_grad_norm":  {"direction": "down", "rel_tol": 1.0,
                             "mad_mult": 5.0},
    "health/ae_param_norm": {"direction": "down", "rel_tol": 1.0,
                             "mad_mult": 5.0},
    "health/ae_nonfinite":  {"direction": "down", "rel_tol": 0.0,
                             "abs_tol": 0.5, "mad_mult": 0.0},
    # perf-microscope attribution gauges (hfrep_tpu/obs/attrib.py;
    # ISSUE 13).  ``dispatch_frac`` is the one that MUST be explicit:
    # "_frac" carries no cost suffix, so the higher-is-better fallback
    # would gate (and cross-host fold) it INVERTED — yet a RISING
    # dispatch fraction means the host, not the chip, is becoming the
    # bottleneck: lower is better.  It sits near 1.0 on a synchronous
    # CPU backend and near 0 on a pipelined TPU drive, so the floor is
    # absolute (a relative tolerance of ~nothing at either extreme
    # would flag scheduler jitter).  The ms splits are costs with
    # generous relative floors — they are attribution evidence for
    # ``obs explain``, not primary gates; steps_per_sec stays the alarm.
    "attrib/dispatch_ms":   {"direction": "down", "rel_tol": 0.25,
                             "mad_mult": 5.0},
    "attrib/compute_ms":    {"direction": "down", "rel_tol": 0.25,
                             "mad_mult": 5.0},
    "attrib/dispatch_frac": {"direction": "down", "rel_tol": 0.0,
                             "abs_tol": 0.10, "mad_mult": 5.0},
    # chaos-search gauges (hfrep_tpu/resilience/chaos.py; ISSUE 14).
    # ``violations`` is the one that MUST be explicit: it has no cost
    # suffix, so the higher-is-better fallback would gate (and
    # cross-host fold) a rising violation count as an improvement —
    # the shed_rate class, on the one gauge whose whole job is to be
    # zero.  ``schedules``/``subjects`` are coverage floors (a soak
    # that silently drove fewer schedules is the disarmed-gate failure
    # mode, absolute floors — counts are exact at a fixed seed);
    # ``run_secs`` is a cost with a generous relative floor (spawned
    # subprocess wall clocks are host-load noisy).
    "chaos/schedules":      {"direction": "up",   "rel_tol": 0.0,
                             "abs_tol": 0.5, "mad_mult": 0.0},
    "chaos/subjects":       {"direction": "up",   "rel_tol": 0.0,
                             "abs_tol": 0.5, "mad_mult": 0.0},
    "chaos/violations":     {"direction": "down", "rel_tol": 0.0,
                             "abs_tol": 0.5, "mad_mult": 0.0},
    "chaos/run_secs":       {"direction": "down", "rel_tol": 0.50,
                             "mad_mult": 5.0},
    # fleet-watcher gauges (hfrep_tpu/obs/fleet.py; ISSUE 17).  The
    # invariant trio — ``ledger_deficit``/``breakers_open``/
    # ``restart_storms`` — exists to be ZERO, the shed_rate class with
    # exact floors (any nonzero value is already an incident; gating
    # re-litigates it).  ``submitted``/``terminal`` are raw ledger
    # sides: "down" would read MORE traffic as a regression and "up"
    # would read a quieter soak as one, so both get wide relative
    # floors and exist mainly so the cross-host fold direction is
    # explicit.  ``replicas`` is a structural coverage floor (a fleet
    # that silently lost a replica dir is the disarmed-gate failure
    # mode); ``restarts`` tolerates supervision churn but flags storms
    # via its dedicated zero-floor gauge.
    "fleet/replicas":        {"direction": "up",   "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    "fleet/submitted":       {"direction": "up",   "rel_tol": 0.50,
                              "mad_mult": 5.0},
    "fleet/terminal":        {"direction": "up",   "rel_tol": 0.50,
                              "mad_mult": 5.0},
    "fleet/ledger_deficit":  {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    "fleet/breakers_open":   {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    "fleet/restarts":        {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 2.0, "mad_mult": 5.0},
    "fleet/restart_storms":  {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    # SLO burn-rate gauges (hfrep_tpu/obs/slo.py; ISSUE 17).
    # ``worst_burn`` is the one that MUST be explicit: "_burn" carries
    # no cost suffix, so the higher-is-better fallback would gate a
    # rising burn rate — budget consumed FASTER — as an improvement,
    # exactly the inverted-shed_rate failure mode the satellite calls
    # out.  Burn sits anywhere in [0, 1) on a healthy fleet, so the
    # floor is absolute slack below the 1.0 alert line, not relative.
    # ``breaches``/``warnings`` exist to be zero (exact floors);
    # ``evaluated`` is a coverage floor (a run that silently evaluated
    # fewer objectives must not pass as "no breaches").
    "slo/evaluated":         {"direction": "up",   "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    "slo/breaches":          {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    "slo/warnings":          {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 0.5, "mad_mult": 0.0},
    "slo/worst_burn":        {"direction": "down", "rel_tol": 0.0,
                              "abs_tol": 0.25, "mad_mult": 5.0},
    # wall-clock ledger gauges (hfrep_tpu/obs/timeline.py; ISSUE 18).
    # Every ``timeline/*`` row is explicit — "_frac" carries no cost
    # suffix, so EVERY fraction here would hit the higher-is-better
    # fallback inverted (the shed_rate class, again).  The two gated
    # hygiene fractions use absolute floors near zero: a healthy drive
    # keeps ``obs_self_frac`` under 1% (the <0.01 acceptance gate — the
    # observer must not become the observed) and ``unattributed_frac``
    # small, where any relative tolerance is ~nothing and would flag
    # scheduler jitter.  ``device_compute_frac`` is the one
    # higher-is-better fraction (more of the wall on the chip);
    # dispatch/host_io/checkpoint/queue_wait are overheads.
    # ``overlap_frac`` is ROADMAP item 2(a)'s before-measurement:
    # higher = more host work hidden behind device execution.
    # ``wall_ms`` is a cost with a wide floor (whole-drive wall clocks
    # are host-load noisy; steps_per_sec stays the primary alarm).
    "timeline/device_compute_frac": {"direction": "up",   "rel_tol": 0.0,
                                     "abs_tol": 0.10, "mad_mult": 5.0},
    "timeline/dispatch_frac":       {"direction": "down", "rel_tol": 0.0,
                                     "abs_tol": 0.10, "mad_mult": 5.0},
    "timeline/host_io_frac":        {"direction": "down", "rel_tol": 0.0,
                                     "abs_tol": 0.05, "mad_mult": 5.0},
    "timeline/checkpoint_frac":     {"direction": "down", "rel_tol": 0.0,
                                     "abs_tol": 0.05, "mad_mult": 5.0},
    "timeline/queue_wait_frac":     {"direction": "down", "rel_tol": 0.0,
                                     "abs_tol": 0.05, "mad_mult": 5.0},
    "timeline/obs_self_frac":       {"direction": "down", "rel_tol": 0.0,
                                     "abs_tol": 0.01, "mad_mult": 0.0},
    "timeline/unattributed_frac":   {"direction": "down", "rel_tol": 0.0,
                                     "abs_tol": 0.10, "mad_mult": 5.0},
    "timeline/overlap_frac":        {"direction": "up",   "rel_tol": 0.0,
                                     "abs_tol": 0.10, "mad_mult": 5.0},
    "timeline/wall_ms":             {"direction": "down", "rel_tol": 0.50,
                                     "mad_mult": 5.0},
    # Drive runtime gauges (hfrep_tpu/resilience/drive.py; ISSUE 20).
    # ``drive/secs`` is the envelope's whole-drive wall clock — a cost
    # with the same wide floor as ``timeline/wall_ms`` (host-load noisy;
    # the per-phase alarms stay primary).  ``drive/boundaries`` is a
    # counter (never indexed by the history store) but it still needs
    # the explicit HF001 row for the fold direction: MORE boundary
    # crossings per drive means finer drain granularity — a run that
    # silently crosses fewer safe points is the regression.
    "drive/secs":                   {"direction": "down", "rel_tol": 0.50,
                                     "mad_mult": 5.0},
    "drive/boundaries":             {"direction": "up",   "rel_tol": 0.0,
                                     "abs_tol": 0.5, "mad_mult": 5.0},
}

#: fallback rule for metrics without an entry above (bench gauges are
#: throughput-like by convention: higher is better).  The suffix check
#: in :func:`_rule_for` flips direction for names that are self-evidently
#: costs — a future ``bench/foo_time_ms`` gauge must not gate inverted
#: just because nobody added a table entry.
DEFAULT_RULE = {"direction": "up", "rel_tol": 0.05, "mad_mult": 5.0}

#: name suffixes that mark a metric as a cost (lower is better) when it
#: has no explicit table entry.  Checked only after the rate suffixes —
#: ``*_per_sec`` stays higher-is-better even though it ends in ``_sec``.
_RATE_SUFFIXES = ("_per_sec", "_per_s", "/sec", "_rate", "_mfu")
_COST_SUFFIXES = ("_ms", "_secs", "_sec", "_s", "_time", "_bytes", "_div",
                  "_loss", "_count", "_compiles")

#: MAD -> σ consistency constant under normality
MAD_TO_SIGMA = 1.4826

DEFAULT_WINDOW = 8
DEFAULT_MIN_RUNS = 3


def mad(values: List[float], center: Optional[float] = None) -> float:
    """Median absolute deviation around ``center`` (default: the median)."""
    c = median(values) if center is None else center
    return median([abs(v - c) for v in values])


def trend_slope(values: List[float]) -> Optional[float]:
    """Ordinary-least-squares slope of ``values`` against run index —
    units per run; ``None`` below 3 samples (two points always fit a
    line, proving nothing about a *trend*)."""
    n = len(values)
    if n < 3:
        return None
    x_bar = (n - 1) / 2.0
    y_bar = sum(values) / n
    den = sum((i - x_bar) ** 2 for i in range(n))
    return sum((i - x_bar) * (y - y_bar)
               for i, y in enumerate(values)) / den


def comparable_series(records: List[dict], key: dict,
                      metric: str) -> List[float]:
    """The metric's samples from records sharing the comparability key,
    oldest-first (history is append-only, so file order IS time order)."""
    out = []
    for rec in records:
        if rec.get("key") != key:
            continue
        v = _num((rec.get("metrics") or {}).get(metric))
        if v is not None:
            out.append(float(v))
    return out


def _rule_for(metric: str, thresholds: Optional[dict]) -> dict:
    if metric in DEFAULT_THRESHOLDS:
        base = dict(DEFAULT_THRESHOLDS[metric])
    else:
        base = dict(DEFAULT_RULE)
        low = metric.lower()
        if (not low.endswith(_RATE_SUFFIXES)
                and low.endswith(_COST_SUFFIXES)):
            base["direction"] = "down"
    if thresholds and metric in thresholds:
        override = thresholds[metric]
        if isinstance(override, dict):
            base.update(override)
        else:
            # bare number = EXACT relative tolerance: an explicit
            # per-metric tolerance replaces the adaptive MAD term rather
            # than being maxed against it (otherwise a tight override
            # could never tighten a noisy series' gate)
            base["rel_tol"] = float(override)
            base["mad_mult"] = 0.0
            base["abs_tol"] = 0.0
    return base


def check_metric(metric: str, observed, series: List[float], *,
                 thresholds: Optional[dict] = None,
                 min_runs: int = DEFAULT_MIN_RUNS,
                 window: int = DEFAULT_WINDOW) -> dict:
    """One metric's gate decision against its comparable series.

    Returns ``{metric, status, baseline, observed, threshold, n, mad}``
    with ``status`` in ``ok`` / ``regression`` / ``insufficient-history``
    / ``missing`` (the run did not measure the metric — never a failure:
    a CPU fixture has no device memory stats).
    """
    rule = _rule_for(metric, thresholds)
    tail = series[-max(1, int(window)):]
    # the enforcement floor can never exceed the window: --window 2
    # --min-runs 3 would otherwise park every check in
    # insufficient-history forever — a green gate that never gates
    need = max(1, min(int(min_runs), max(1, int(window))))
    value = _num(observed)     # ingest's filter: bool/NaN/inf are absent
    if value is None:
        return {"metric": metric, "status": "missing", "baseline": None,
                "observed": None, "threshold": None, "n": len(tail),
                "mad": None}
    if len(tail) < need:
        return {"metric": metric, "status": "insufficient-history",
                "baseline": median(tail) if tail else None,
                "observed": float(value), "threshold": None,
                "n": len(tail), "mad": mad(tail) if tail else None}
    base = median(tail)
    spread = mad(tail, base)
    allowed = max(float(rule.get("rel_tol", 0.0)) * abs(base),
                  float(rule.get("mad_mult", 0.0)) * MAD_TO_SIGMA * spread,
                  float(rule.get("abs_tol", 0.0)))
    delta = (base - float(value) if rule["direction"] == "up"
             else float(value) - base)          # positive = got worse
    status = "regression" if delta > allowed else "ok"
    check = {"metric": metric, "status": status,
             "baseline": round(base, 9), "observed": float(value),
             "threshold": round(allowed, 9), "delta": round(delta, 9),
             "direction": rule["direction"], "n": len(tail),
             "mad": round(spread, 9)}

    # Trend-slope drift tracking: a sequence of sub-threshold moves —
    # each inside the level gate, all in the worsening direction — is
    # exactly the BENCH_r01-r05 pattern the level baseline structurally
    # misses (the rolling median follows the drift down).  Fit a slope
    # over the window *plus this run*; when the cumulative drift it
    # projects across that span exceeds the level gate's rel/abs floors,
    # flag ``drift: true``.  The MAD term is deliberately NOT part of
    # the drift floor: a trending series inflates its own MAD, so the
    # adaptive term that protects the level gate from noise would blind
    # the trend check to exactly the pattern it exists to catch.
    # Warn-only: a slope is an extrapolation, not an observation, so it
    # colors the verdict without failing the gate.
    trend = tail + [float(value)]
    slope = trend_slope(trend)
    if slope is not None and base:
        slope_frac = slope / abs(base)
        worsening = slope < 0 if rule["direction"] == "up" else slope > 0
        projected = abs(slope) * (len(trend) - 1)
        drift_floor = max(float(rule.get("rel_tol", 0.0)) * abs(base),
                          float(rule.get("abs_tol", 0.0)))
        check["slope"] = round(slope, 9)
        check["slope_frac"] = round(slope_frac, 9)
        check["drift"] = bool(status == "ok" and worsening
                              and drift_floor > 0
                              and projected > drift_floor)
    return check


def check_run(record: dict, records: List[dict], *,
              thresholds: Optional[dict] = None,
              min_runs: int = DEFAULT_MIN_RUNS,
              window: int = DEFAULT_WINDOW,
              metrics: Optional[List[str]] = None) -> dict:
    """Gate one run record against the history: the full verdict.

    ``records`` may or may not already contain this run — a sample with
    the same (run_id, created_unix) is excluded from its own baseline,
    so gate-after-ingest and gate-before-ingest agree.
    """
    key = record.get("key") or {}
    prior = [r for r in records
             if not (r.get("run_id") == record.get("run_id")
                     and r.get("created_unix") == record.get("created_unix"))]
    names = metrics if metrics is not None else list(
        (record.get("metrics") or {}).keys())
    checks = [
        check_metric(m, (record.get("metrics") or {}).get(m),
                     comparable_series(prior, key, m),
                     thresholds=thresholds, min_runs=min_runs, window=window)
        for m in names]
    regressions = [c for c in checks if c["status"] == "regression"]
    # a run that measured NOTHING (every check "missing" — empty event
    # stream, OOM-killed before the first flush, broken emission) must
    # not gate green: exit-0-with-zero-evidence is the silently-disarmed
    # sentinel this module exists to close.  Individual missing metrics
    # stay non-failing; it is the total absence that fails.
    no_data = not any(c["status"] != "missing" for c in checks)
    return {
        "v": 2,
        "run_id": record.get("run_id"),
        "git_sha": record.get("git_sha"),
        "key": key,
        "ok": not regressions and not no_data,
        "no_data": no_data,
        "n_comparable": len([r for r in prior if r.get("key") == key]),
        "regressions": [c["metric"] for c in regressions],
        # sustained sub-threshold drift (warn-only; never flips ``ok``)
        "drifts": [c["metric"] for c in checks if c.get("drift")],
        "checks": checks,
    }


# ------------------------------------------------------------- rendering
_STATUS_GLYPH = {"ok": "ok  ", "regression": "FAIL", "missing": "--  ",
                 "insufficient-history": "n={n} "}


def render_verdict(verdict: dict) -> str:
    """Human verdict: one line per metric, worst news first."""
    word = ("NO-DATA" if verdict.get("no_data")
            else "PASS" if verdict["ok"] else "REGRESSION")
    drifts = verdict.get("drifts") or []
    head = word + (
        f"  run {verdict['run_id']}  (key: "
        f"family={verdict['key'].get('family')}, "
        f"shape={verdict['key'].get('shape')}, "
        f"host={verdict['key'].get('host')}, "
        f"backend={verdict['key'].get('backend')}, "
        f"mesh={verdict['key'].get('mesh')}; "
        f"{verdict['n_comparable']} comparable runs)")
    if drifts:
        head += (f"\nDRIFT WARNING: sustained sub-threshold trend on "
                 f"{', '.join(drifts)} (slope below; level gate not tripped)")
    order = {"regression": 0, "ok": 1, "insufficient-history": 2,
             "missing": 3}
    lines = [head]
    for c in sorted(verdict["checks"],
                    key=lambda c: (order[c["status"]],
                                   not c.get("drift"))):
        glyph = _STATUS_GLYPH[c["status"]].format(n=c["n"])
        if c["status"] == "missing":
            lines.append(f"  {glyph} {c['metric']:26s} (not measured)")
            continue
        base = "-" if c["baseline"] is None else f"{c['baseline']:.6g}"
        thr = "-" if c["threshold"] is None else f"{c['threshold']:.3g}"
        line = (f"  {glyph} {c['metric']:26s} observed {c['observed']:.6g}"
                f"  baseline {base} (n={c['n']})  allowed ±{thr}")
        if c.get("slope_frac") is not None:
            line += f"  slope {c['slope_frac'] * 100:+.2f}%/run"
            if c.get("drift"):
                line += "  DRIFT"
        lines.append(line)
    return "\n".join(lines)


def verdict_json(verdict: dict) -> str:
    return json.dumps(verdict, indent=2, default=str)
