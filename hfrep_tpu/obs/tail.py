"""The live plane: ``obs tail`` (follow a running run dir) + ``obs
export`` (Prometheus-exposition snapshots).

The report CLI answers questions *after* a run; nothing answered "is
this run diverging / shedding / breaker-open RIGHT NOW".  ``tail``
follows every ``events*.jsonl`` under the given run dir(s) — the
supervisor's stream plus its actors', a server's stream, a trainer's —
torn-tail-tolerantly (only complete lines are consumed; a partial final
line waits for its newline, exactly the property the buffered writer
guarantees) and renders a refreshing one-screen summary: recent
steps/sec from ``block`` spans, the latest ``health/*`` gauges, queue
depth, shed/deadline counts, circuit-breaker state, event totals.

``export`` writes the same aggregate as a Prometheus exposition-format
text snapshot (gauges, counters as ``_total``, histogram p50/p95/max),
name-sanitized under the ``hfrep_`` prefix — the hand-off point for
external scrapers until a real HTTP exporter is worth its dependencies.

Everything here is stdlib-only, like the rest of the obs read path.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

#: recent-window length for the live steps/sec estimate (block spans)
_RECENT_BLOCKS = 8

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


class TailAggregate:
    """Incremental consumer of event records → the live-view state."""

    def __init__(self):
        self.gauges: Dict[str, float] = {}
        self.counters: Dict[str, float] = {}
        self.hists: Dict[str, dict] = {}
        self.events: Dict[str, int] = {}
        self.n_records = 0
        self.last_t = 0.0
        self.last_event: Optional[str] = None
        self.breaker: Optional[str] = None
        self.blocks: List[Tuple[float, float]] = []   # (steps, dur)
        self.run_end = False

    def consume(self, rec: dict) -> None:
        self.n_records += 1
        t = rec.get("t")
        if isinstance(t, (int, float)):
            self.last_t = max(self.last_t, float(t))
        rtype = rec.get("type")
        if rtype == "metric":
            name, value = str(rec.get("name")), rec.get("value")
            if rec.get("kind") == "gauge":
                if isinstance(value, (int, float)):
                    self.gauges[name] = float(value)
            elif rec.get("kind") == "counter":
                if isinstance(value, (int, float)):
                    self.counters[name] = float(value)
            elif rec.get("kind") == "histogram":
                # the full log-bucket accumulator (same math as the
                # in-process obs.Histogram), not just count/sum/max —
                # the Prometheus exposition derives real cumulative
                # _bucket{le=...} series from it
                from hfrep_tpu.obs import rollup as _rollup
                h = self.hists.setdefault(name, _rollup.new_hist())
                if isinstance(value, (int, float)):
                    _rollup.hist_observe(h, value)
        elif rtype == "span":
            if rec.get("name") == "block" and rec.get("steps"):
                try:
                    self.blocks.append((float(rec["steps"]),
                                        float(rec["dur"])))
                except (TypeError, ValueError):
                    pass
                self.blocks = self.blocks[-_RECENT_BLOCKS:]
        elif rtype == "event":
            name = str(rec.get("name"))
            self.events[name] = self.events.get(name, 0) + 1
            self.last_event = name
            if name == "serve_breaker_open":
                self.breaker = "open"
            elif name == "serve_breaker_close":
                self.breaker = "closed"
            elif name == "run_end":
                self.run_end = True
                summary = rec.get("summary") or {}
                for k, v in (summary.get("gauges") or {}).items():
                    if isinstance(v, (int, float)):
                        self.gauges.setdefault(str(k), float(v))

    # ------------------------------------------------------------ derived
    def steps_per_sec(self) -> Optional[float]:
        if not self.blocks:
            return None
        steps = sum(s for s, _ in self.blocks)
        secs = sum(d for _, d in self.blocks)
        return steps / secs if secs > 0 else None

    def queue_depth(self) -> Optional[float]:
        for name in ("orchestrate/queue_depth", "serve/queue_depth"):
            if name in self.gauges:
                return self.gauges[name]
        return None


def _fmt(v, digits: int = 2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e9:
        return str(int(v))
    return f"{v:.{digits}f}" if isinstance(v, float) else str(v)


def render_frame(aggs: Dict[str, TailAggregate], width: int = 78) -> str:
    """One screen: per-stream-root sections, health and serving state
    called out, everything else summarized."""
    lines = [f"flight recorder — {time.strftime('%H:%M:%S')}"]
    for label, agg in sorted(aggs.items()):
        status = "ended" if agg.run_end else "live"
        lines.append(f"[{label}]  {status}  t={agg.last_t:.1f}s  "
                     f"{agg.n_records} records")
        sps = agg.steps_per_sec()
        if sps is not None:
            lines.append(f"  steps/sec (recent): {sps:.1f}")
        health = {k: v for k, v in agg.gauges.items()
                  if k.startswith("health/")}
        if health:
            lines.append("  health: " + "  ".join(
                f"{k[len('health/'):]}={_fmt(v, 4)}"
                for k, v in sorted(health.items())))
        depth = agg.queue_depth()
        if depth is not None:
            lines.append(f"  queue depth: {_fmt(depth)}")
        serve_bits = []
        if "serve/shed_rate" in agg.gauges:
            serve_bits.append(f"shed_rate={agg.gauges['serve/shed_rate']}")
        for ev in ("serve_shed", "serve_deadline_miss", "serve_degraded"):
            if agg.events.get(ev):
                serve_bits.append(f"{ev.split('serve_')[-1]}={agg.events[ev]}")
        if agg.breaker is not None:
            serve_bits.append(f"breaker={agg.breaker}")
        if serve_bits:
            lines.append("  serving: " + "  ".join(serve_bits))
        faults = {k: v for k, v in agg.events.items()
                  if k in ("numeric_fault", "fault_injected", "io_retry",
                           "preempt_requested", "actor_restart",
                           "crash_bundle")}
        if faults:
            lines.append("  faults: " + "  ".join(
                f"{k}={v}" for k, v in sorted(faults.items())))
        if agg.last_event:
            lines.append(f"  last event: {agg.last_event}")
    return "\n".join(ln[:width] for ln in lines)


# ------------------------------------------------------------- following
class _StreamFollower:
    """Offset-tracking reader of one JSONL file: consumes only complete
    (newline-terminated) lines, so a writer's torn tail is simply
    re-read on the next poll when the rest of the line lands."""

    def __init__(self, path: Path):
        self.path = path
        self.offset = 0

    def poll(self) -> List[dict]:
        out = []
        try:
            with open(self.path) as fh:
                fh.seek(self.offset)
                chunk = fh.read()
        except OSError:
            return out
        end = chunk.rfind("\n")
        if end < 0:
            return out
        for line in chunk[: end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue                    # mid-file garbage: skip a line
            if isinstance(rec, dict):
                out.append(rec)
        self.offset += end + 1
        return out


def _stream_label(path: Path, roots: List[Path]) -> str:
    # rollup chunks/pins are earlier bytes of their run dir's live
    # stream — fold them under the run's label, not a "rollup" row
    parent = path.parent
    if parent.name == "rollup":
        parent = parent.parent
    for root in roots:
        try:
            rel = parent.relative_to(root)
        except ValueError:
            continue
        return root.name if str(rel) in ("", ".") else str(rel)
    return str(parent)


def _discover(run_dirs: List[Path]) -> List[Path]:
    from hfrep_tpu.obs.report import is_stream_file
    out = []
    for d in run_dirs:
        # real streams only: a crash bundle's events_tail.jsonl is a
        # copy of stream tails and would double-count every record.
        # Rollup chunks/pins are earlier bytes of those same streams
        # (writer rotation / compaction evidence) — follow them too, so
        # a long soak's tail/export doesn't go blind at the first
        # rotation.  Fully-compacted aggregates live in rollup
        # state.json; `export --fleet` is the reader for those.
        streams = sorted(f for f in d.rglob("events*.jsonl")
                         if is_stream_file(f))
        for f in streams:
            if f.name == "events.jsonl":
                from hfrep_tpu.obs import rollup as _rollup
                out.extend(_rollup.pinned_files(f.parent))
                out.extend(_rollup.chunk_files(f.parent))
            out.append(f)
    return out


def tail_main(run_dirs, interval: float = 1.0, once: bool = False,
              max_frames: Optional[int] = None,
              out=None) -> int:
    """Follow the run dirs until interrupted (or ``once``/``max_frames``
    for scripting); returns 0.  ``out`` defaults to stdout."""
    out = out or sys.stdout
    roots = [Path(d) for d in run_dirs]
    followers: Dict[Path, _StreamFollower] = {}
    aggs: Dict[str, TailAggregate] = {}
    frames = 0
    clear = not once and out is sys.stdout and out.isatty()
    while True:
        for path in _discover(roots):
            if path not in followers:
                followers[path] = _StreamFollower(path)
        for path, follower in followers.items():
            label = _stream_label(path, roots)
            agg = aggs.setdefault(label, TailAggregate())
            for rec in follower.poll():
                agg.consume(rec)
        if not aggs:
            aggs["(no streams yet)"] = TailAggregate()
        frame = render_frame(aggs)
        if clear:
            out.write("\x1b[2J\x1b[H")
        out.write(frame + "\n")
        out.flush()
        frames += 1
        if once or (max_frames is not None and frames >= max_frames):
            return 0
        try:
            time.sleep(max(0.05, float(interval)))
        except KeyboardInterrupt:
            return 0


# ------------------------------------------------------------ Prometheus
def _prom_name(name: str) -> str:
    return "hfrep_" + _PROM_BAD.sub("_", str(name))


def prometheus_text(aggs: Dict[str, TailAggregate]) -> str:
    """One exposition-format document over every stream, labeled by
    stream root (``{stream="..."}``)."""
    gauges: Dict[str, List[Tuple[str, float]]] = {}
    counters: Dict[str, List[Tuple[str, float]]] = {}
    hists: Dict[str, List[Tuple[str, dict]]] = {}
    for label, agg in sorted(aggs.items()):
        for k, v in agg.gauges.items():
            gauges.setdefault(k, []).append((label, v))
        for k, v in agg.counters.items():
            counters.setdefault(k, []).append((label, v))
        for k, h in agg.hists.items():
            hists.setdefault(k, []).append((label, h))
    lines = []

    def esc(label: str) -> str:
        return label.replace("\\", "\\\\").replace('"', '\\"')

    for name in sorted(gauges):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} gauge")
        for label, v in gauges[name]:
            lines.append(f'{pname}{{stream="{esc(label)}"}} {v}')
    for name in sorted(counters):
        pname = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pname} counter")
        for label, v in counters[name]:
            lines.append(f'{pname}{{stream="{esc(label)}"}} {v}')
    for name in sorted(hists):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for label, h in hists[name]:
            # proper cumulative buckets from the log-bucket accumulator:
            # le = each bucket's exact upper edge (10**((idx+1)/100)),
            # zero/negative samples folded in from the smallest bucket
            # up, closed by the mandatory le="+Inf" = _count
            from hfrep_tpu.obs import rollup as _rollup
            for le, cum in _rollup.hist_cumulative(h):
                lines.append(f'{pname}_bucket{{stream="{esc(label)}",'
                             f'le="{le}"}} {cum}')
            lines.append(
                f'{pname}_count{{stream="{esc(label)}"}} {h["n"]}')
            lines.append(f'{pname}_sum{{stream="{esc(label)}"}} {h["sum"]}')
            if h["max"] is not None:
                lines.append(
                    f'{pname}_max{{stream="{esc(label)}"}} {h["max"]}')
    return "\n".join(lines) + "\n"


def export_main(run_dirs, out: Optional[str] = None) -> int:
    """Read the run dirs to completion and emit one Prometheus snapshot
    (stdout, or ``out`` via tmp + atomic rename)."""
    roots = [Path(d) for d in run_dirs]
    aggs: Dict[str, TailAggregate] = {}
    for path in _discover(roots):
        agg = aggs.setdefault(_stream_label(path, roots), TailAggregate())
        for rec in _StreamFollower(path).poll():
            agg.consume(rec)
    if not aggs:
        print(f"no events*.jsonl under {', '.join(map(str, run_dirs))}",
              file=sys.stderr)
        return 1
    text = prometheus_text(aggs)
    if out is None:
        sys.stdout.write(text)
        return 0
    dst = Path(out)
    tmp = dst.with_name(dst.name + f".tmp-{os.getpid()}")
    tmp.write_text(text)
    os.replace(tmp, dst)
    return 0
