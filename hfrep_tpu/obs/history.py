"""Run-history store: obs run dirs -> a compact append-only ``history.jsonl``.

The report CLI (PR 2) answers "what did THIS run do" and can diff two
dirs by hand; nothing in the repo *remembers* past runs, which is why
the BENCH_r01-r05 steps/sec drift (555.5 vs 591.6 at baseline) had to be
spotted by a human reading five JSON files.  This module is the memory:

* :func:`ingest` summarizes one run directory (``run.json`` +
  ``events.jsonl``, torn tails tolerated — crashed runs are exactly the
  ones worth remembering) into ONE index line and appends it to a
  history file;
* :func:`ingest_multihost` first folds the per-process run dirs a
  multi-host launch writes (``<dir>/proc0``, ``proc1``, ...) into one
  logical run (:func:`merge_run_dirs`) and ingests that;
* :func:`load_history` reads the index back, with the same torn-final-
  line tolerance as the event stream (the history file is itself an
  append-only JSONL a killed CI job may tear).

Each line is schema v2 (:data:`HISTORY_SCHEMA_VERSION`) and carries a
**comparability key** — ``(family, shape, mesh, host, backend)`` — so
the regression engine (:mod:`hfrep_tpu.obs.regress`) only ever baselines
a run against runs of the same program shape on the same hardware; a
laptop CPU run can never drag down a pod's baseline, and a window=168
production-shape run can never blend into a window=48 headline series
(the two differ ~3.5x in steps/sec by design, not by regression).
Per-metric series over that key are what "keyed by (metric, family,
mesh, host)" means — one line per run, one series per metric within it.

Everything here is stdlib-only (no jax import): ingestion runs in CI and
on login nodes where initializing a backend is either slow or wrong.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from hfrep_tpu.obs.report import SchemaError, load_jsonl, summarize

HISTORY_SCHEMA_VERSION = 2

#: the summary fields every history record carries (the regression
#: engine's default metric universe; ``None`` where a run lacks one)
METRIC_FIELDS = (
    "steps_per_sec",
    "step_time_p50_s",
    "step_time_p95_s",
    "mfu",
    "memory_high_water_bytes",
    "backend_compiles",
    "compile_secs",
)

#: gauge-name prefixes whose values ride into the record verbatim — the
#: bench probes' ``bench/<name>`` emissions, the serving layer's
#: ``serve/<name>`` gauges, the scenario factory's ``scenario/<name>``
#: gauges, the flight recorder's ``health/<name>`` gauges and the perf
#: microscope's ``attrib/<name>`` dispatch/compute splits become
#: first-class history metrics without the store having to know each
#: probe's vocabulary
GAUGE_PREFIXES = ("bench/", "serve/", "scenario/", "health/", "attrib/",
                  "chaos/", "fleet/", "slo/", "timeline/", "drive/")
BENCH_GAUGE_PREFIX = "bench/"          # back-compat alias

#: deadline-class ladder for the serve shape signature: a 10ms-deadline
#: series and a 1s-deadline series measure different regimes (shed-bound
#: vs batch-bound) and must never share a baseline
_DEADLINE_CLASSES = (10, 25, 50, 100, 250, 500, 1000)


def _num(v) -> Optional[float]:
    """JSON-safe numeric or None (nan/inf collapse to None: a metric the
    run could not measure is absent, not a poisoned baseline sample)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if v != v or v in (float("inf"), float("-inf")):
        return None
    return v


def _deadline_class(ms) -> str:
    """``deadline_ms`` → its ladder class (``d100`` = the 50–100ms
    band; ``dinf`` beyond the ladder)."""
    v = _num(ms)
    if v is None:
        return "d?"
    for bound in _DEADLINE_CLASSES:
        if v <= bound:
            return f"d{bound}"
    return "dinf"


def _shape_sig(cfg: dict) -> Optional[str]:
    """Compact program-shape signature from the annotated config —
    ``w48f35h100b32`` for the headline bench shape.  Family alone is not
    a shape: a window=168 production run and a window=48 headline run of
    the same family differ ~3.5x in steps/sec by construction, and
    blending their series would bake a baseline no shape ever ran.
    Runs that never annotated a config (manual ``enable()`` callers)
    yield None and compare only with other shapeless runs.

    Serve runs get their OWN signature — ``svb<max_batch><deadline
    class>`` from the annotated ``serve`` section (batch bucket ×
    deadline class, e.g. ``svb8d250``) — so a serving run's latency/QPS
    series can never blend into a training run's steps/sec series even
    when both annotate the same model family.

    Scenario runs likewise — ``scnf<funds>m<months>w<windows>l<latents>``
    from the annotated ``scenario`` section (the svb pattern): a
    walk-forward/universe drive's windows-per-sec series must never
    blend into a GAN training steps/sec series, and two universe sizes
    are different workloads by construction."""
    scenario = cfg.get("scenario") or {}
    if scenario:
        return "scnf{}m{}w{}l{}".format(
            scenario.get("funds", "?"), scenario.get("months", "?"),
            scenario.get("windows", "?"), scenario.get("latents", "?"))
    serve = cfg.get("serve") or {}
    if serve:
        return "svb{}{}".format(serve.get("max_batch", "?"),
                                _deadline_class(serve.get("deadline_ms")))
    model = cfg.get("model") or {}
    train = cfg.get("train") or {}
    parts = (model.get("window"), model.get("features"),
             model.get("hidden"), train.get("batch_size"))
    if all(p is None for p in parts):
        return None
    return "w{}f{}h{}b{}".format(*("?" if p is None else p for p in parts))


def run_key(manifest: dict) -> Dict[str, object]:
    """The comparability key of a run: only runs with an identical key
    share a baseline series.  ``shape`` is the program-shape signature
    (:func:`_shape_sig`); ``mesh`` is the trainer-annotated mesh shape
    dict (None for single-device runs), so a dp=8 pod run and a laptop
    run index different series even on equal family and shape."""
    cfg = manifest.get("config") or {}
    model = cfg.get("model") or {}
    return {
        "family": model.get("family"),
        "shape": _shape_sig(cfg),
        "mesh": manifest.get("mesh"),
        "host": (manifest.get("host") or {}).get("hostname"),
        "backend": (manifest.get("devices") or {}).get("backend"),
    }


def record_from_summary(summary: dict, manifest: dict, *,
                        hosts: int = 1) -> dict:
    """One history line from a (summary, manifest) pair — the pure core
    shared by single-host and merged multi-host ingestion."""
    metrics = {k: _num(summary.get(k)) for k in METRIC_FIELDS}
    if not metrics.get("memory_high_water_bytes"):
        # the summary reports 0 when a run emitted no memory events at
        # all; a literal zero-byte "baseline" would flag every later
        # real measurement as a regression — absent, not zero
        metrics["memory_high_water_bytes"] = None
    for name, value in (summary.get("gauges") or {}).items():
        if str(name).startswith(GAUGE_PREFIXES):
            metrics[str(name)] = _num(value)
    return {
        "v": HISTORY_SCHEMA_VERSION,
        "kind": "run",
        "run_id": summary.get("run_id"),
        "run_dir": summary.get("run_dir"),
        "created_unix": _num(manifest.get("created_unix")),
        "git_sha": (manifest.get("git") or {}).get("sha"),
        "key": run_key(manifest),
        "hosts": int(hosts),
        "steps": _num(summary.get("steps")),
        "metrics": metrics,
    }


def _read_manifest_lenient(run_dir) -> dict:
    from hfrep_tpu.obs.manifest import read_manifest
    try:
        return read_manifest(run_dir)
    except (OSError, json.JSONDecodeError):
        return {}


def summarize_run(run_dir) -> dict:
    """(summary + manifest) -> one un-appended history record."""
    return record_from_summary(summarize(run_dir),
                               _read_manifest_lenient(run_dir))


# -------------------------------------------------- cross-host aggregation
def find_proc_dirs(parent_dir) -> List[Path]:
    """The per-process run dirs of a multi-host launch: every immediate
    subdirectory holding an ``events.jsonl`` (the CLI names them
    ``proc<i>``, but the shape — not the name — is the contract)."""
    parent = Path(parent_dir)
    return sorted(d for d in parent.iterdir()
                  if d.is_dir() and (d / "events.jsonl").exists())


def _fold(values, fold) -> Optional[float]:
    nums = [v for v in values if _num(v) is not None]
    return fold(nums) if nums else None


def fold_gauges(summaries: List[dict]) -> Dict[str, float]:
    """Pod-conservative fold of the per-host gauge vectors: for each
    gauge name, **min** over hosts when higher is better (the slowest
    host is the pod's true rate — same argument as steps/sec) and
    **max** when the gauge is a cost (time, memory, divergence).
    Direction comes from the regression engine's per-metric rules
    (:func:`hfrep_tpu.obs.regress._rule_for` — table entry or name-
    suffix heuristic), so the fold and the gate can never disagree
    about which way a gauge points.  Replaces the leader's-gauges
    shortcut (ROADMAP open item): a ``bench/*`` gauge emitted by every
    host now baselines the pod's worst, not whichever host was proc0."""
    from hfrep_tpu.obs import regress

    votes: Dict[str, List[float]] = {}
    for s in summaries:
        for name, value in (s.get("gauges") or {}).items():
            if _num(value) is not None:
                votes.setdefault(str(name), []).append(float(value))
    return {
        name: (min(vals)
               if regress._rule_for(name, None)["direction"] == "up"
               else max(vals))
        for name, vals in votes.items()}


def merge_run_dirs(parent_dir) -> dict:
    """Fold a multi-host launch's per-process run dirs into ONE logical
    run summary (same shape as :func:`hfrep_tpu.obs.report.summarize`,
    plus ``hosts``/``proc_dirs``).

    Fold rules are pod-conservative — the number the merged run reports
    is the one that gates the whole pod:

    * ``steps_per_sec`` / ``mfu`` — **min** over processes (SPMD runs in
      lockstep; the slowest host is the pod's true rate, and a straggler
      should *look* like a regression, not be averaged away);
    * ``step_time_p50_s`` / ``p95`` — **max** (same argument);
    * ``memory_high_water_bytes`` — **max** (the first host to OOM kills
      every process);
    * ``backend_compiles`` / ``compile_secs`` — **sum** (each process
      compiles its own programs; total host-side compile work);
    * ``steps`` — the leader's (processes disagree only when a launch
      died asymmetrically; the leader's count is then the survivors'
      floor and a warning goes to stderr);
    * gauges — per-name pod-conservative fold over the per-host gauge
      vectors (:func:`fold_gauges`: min where higher is better, max for
      costs).

    Leader (first dir, lowest process index by sort order) supplies the
    identity fields.
    """
    dirs = find_proc_dirs(parent_dir)
    if not dirs:
        raise SchemaError(f"{parent_dir}: no per-process run dirs "
                          "(subdirectories holding events.jsonl) to merge")
    summaries = [summarize(d) for d in dirs]
    leader = summaries[0]

    steps = [s.get("steps") for s in summaries]
    if len({int(v) for v in steps if _num(v) is not None}) > 1:
        print(f"warning: {parent_dir}: processes disagree on step count "
              f"{steps} (asymmetric crash?); using the leader's",
              file=sys.stderr)

    merged = dict(leader)
    merged["run_dir"] = str(parent_dir)
    merged["run_id"] = Path(parent_dir).name
    merged["hosts"] = len(dirs)
    merged["proc_dirs"] = [str(d) for d in dirs]
    merged["n_events"] = sum(s["n_events"] for s in summaries)
    merged["blocks"] = {
        "n": sum(s["blocks"]["n"] for s in summaries),
        "steady": sum(s["blocks"]["steady"] for s in summaries),
        "warmup": sum(s["blocks"]["warmup"] for s in summaries),
    }
    for metric, fold in (("steps_per_sec", min), ("mfu", min),
                         ("step_time_p50_s", max), ("step_time_p95_s", max),
                         ("memory_high_water_bytes", max),
                         ("backend_compiles", sum), ("compile_secs", sum)):
        merged[metric] = _fold([s.get(metric) for s in summaries], fold)
    merged["gauges"] = fold_gauges(summaries)
    merged["per_host"] = {
        Path(d).name: {m: _num(s.get(m)) for m in METRIC_FIELDS}
        for d, s in zip(merged["proc_dirs"], summaries)}
    return merged


def merged_record(parent_dir) -> dict:
    """One history line for a whole multi-host launch.

    The key's ``host`` is pod-derived — ``pod<n>:<lexicographic-min
    hostname>`` over ALL processes — not the leader's hostname: a
    scheduler that places proc0 on a different node each launch would
    otherwise start a fresh series every run (every gate forever
    insufficient-history: the silent-disarm mode the sentinel exists to
    close), and a single ``proc0`` ingested without ``--merge`` (un-folded
    metrics) could collide with the pod's folded baseline."""
    dirs = find_proc_dirs(parent_dir)
    merged = merge_run_dirs(parent_dir)
    manifests = [_read_manifest_lenient(d) for d in dirs]
    record = record_from_summary(merged, manifests[0], hosts=len(dirs))
    hostnames = sorted({h for m in manifests
                        if (h := (m.get("host") or {}).get("hostname"))})
    record["key"]["host"] = (
        f"pod{len(dirs)}:{hostnames[0]}" if hostnames else None)
    return record


# --------------------------------------------------------------- the store
def parse_record(line: str, lineno: int = 0) -> Optional[dict]:
    """Parse + validate one history line; blank lines return None."""
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except json.JSONDecodeError as e:
        raise SchemaError(f"line {lineno}: not JSON ({e})") from e
    if not isinstance(rec, dict):
        raise SchemaError(f"line {lineno}: record must be an object")
    if rec.get("v") != HISTORY_SCHEMA_VERSION:
        raise SchemaError(f"line {lineno}: history schema {rec.get('v')!r}, "
                          f"expected {HISTORY_SCHEMA_VERSION}")
    for field in ("kind", "run_id", "key", "metrics"):
        if field not in rec:
            raise SchemaError(f"line {lineno}: record missing {field!r}")
    return rec


def load_history(history_path, strict: bool = False) -> List[dict]:
    """Parse + validate the history index; ``[]`` when absent.

    Same torn-final-line policy as the event stream — both go through
    :func:`hfrep_tpu.obs.report.load_jsonl`, so the tail handling cannot
    silently diverge between the two append-only files: a torn final
    line is dropped with a warning (``strict=True`` — the self-test —
    raises instead); mid-file garbage or an unknown schema still raises.
    """
    path = Path(history_path)
    if not path.exists():
        return []
    return load_jsonl(path, parse_record, strict=strict,
                      torn_hint="writer was likely killed mid-append")


def _repair_torn_tail(path: Path) -> None:
    """Repair an unterminated final line before appending.  Writing
    straight after it would fuse the new record onto the fragment and
    turn recoverable tail damage into permanent MID-file garbage that
    fails every later load.  Mirror the reader's policy
    (:func:`load_history`): a fragment that parses as a complete record
    is data the reader accepts — it just gains its missing newline; one
    that does not parse is exactly what the reader would drop, so
    truncate it away."""
    try:
        size = path.stat().st_size
    except OSError:
        return
    if not size:
        return
    with open(path, "rb+") as fh:
        fh.seek(-1, 2)
        if fh.read(1) == b"\n":
            return
        fh.seek(0)
        data = fh.read()
        keep = data.rfind(b"\n") + 1       # 0 when no newline at all
        try:
            parse_record(data[keep:].decode())
        except (SchemaError, UnicodeDecodeError):
            fh.truncate(keep)
            print(f"warning: {path}: truncated torn final line before "
                  "append (writer was likely killed mid-append)",
                  file=sys.stderr)
        else:
            fh.write(b"\n")                # complete record, torn newline


def append_record(history_path, record: dict,
                  records: Optional[List[dict]] = None) -> bool:
    """Append one record; returns False (no write) when an identical
    (run_id, created_unix) pair is already indexed — re-running a CI
    ingest step must not double-count a run in its own baseline.

    ``records``: the already-loaded index, when the caller just gated
    against it (the gate paths otherwise parse the whole file twice per
    run, O(n²) over the store's life).
    """
    existing = load_history(history_path) if records is None else records
    for rec in existing:
        if (rec.get("run_id") == record.get("run_id")
                and rec.get("created_unix") == record.get("created_unix")):
            return False
    path = Path(history_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    _repair_torn_tail(path)
    with open(path, "a") as fh:
        fh.write(json.dumps(record, default=str) + "\n")
    return True


def ingest(run_dir, history_path) -> dict:
    """Summarize ``run_dir`` and append it to the history index.  The
    returned record gains ``"ingested": bool`` (False = duplicate)."""
    record = summarize_run(run_dir)
    record = dict(record, ingested_unix=round(time.time(), 3))
    record["ingested"] = append_record(history_path, record)
    return record


def ingest_multihost(parent_dir, history_path) -> dict:
    """Fold a multi-host launch's per-process dirs into one logical run
    and append THAT — the pod regresses as a unit, so it baselines as
    a unit (ROADMAP cross-host-aggregation gap)."""
    record = merged_record(parent_dir)
    record = dict(record, ingested_unix=round(time.time(), 3))
    record["ingested"] = append_record(history_path, record)
    return record


# ------------------------------------------------- bench-probe plumbing
def default_store() -> Optional[Path]:
    """The repo-committed bench history store
    (``hfrep_tpu/obs/_bench_history/history.jsonl``), or None when this
    checkout does not carry one.  With it present, the bench probes gate
    and auto-ingest under ``HFREP_OBS_DIR`` alone — the driver's
    ``BENCH_r{N}`` runs accumulate into a committed baseline series
    instead of requiring ``HFREP_HISTORY`` as a second env var
    (ROADMAP sentinel gap)."""
    path = Path(__file__).resolve().parent / "_bench_history" / "history.jsonl"
    return path if path.exists() else None


def resolve_history(obs_dir) -> Optional[str]:
    """The history store a bench probe should gate against:
    ``$HFREP_HISTORY`` when set, else the repo-default store — but the
    default only arms when a run dir is actually being recorded (without
    ``obs_dir`` there is nothing to gate, and the probe should stay a
    plain measurement, not warn about a tripwire nobody armed)."""
    import os
    hist = os.environ.get("HFREP_HISTORY")
    if hist:
        return hist
    if not obs_dir:
        return None
    store = default_store()
    if store:
        print(f"bench: gating against repo-default history {store}",
              file=sys.stderr)
        return str(store)
    return None


def gate_and_ingest(run_dir, history_path, rc: int = 0) -> int:
    """The bench probes' shared perf-sentinel tail: gate ``run_dir``
    against the rolling baseline, ingest it on a fully clean run, and
    return the updated exit code.

    Exit-code split (the driver records ``rc``): a regression — floor or
    history — is 1; a *tooling* failure (corrupt/unreadable store) raises
    ``SystemExit(2)`` so a perf code is never recategorized, except that
    an already-failing ``rc`` outranks the tooling error."""
    from hfrep_tpu.obs import regress

    try:
        record = summarize_run(run_dir)
        records = load_history(history_path)
        verdict = regress.check_run(record, records)
    except (OSError, SchemaError, ValueError) as e:
        print(f"bench: history gate unavailable ({e})", file=sys.stderr)
        raise SystemExit(rc or 2)
    print(regress.render_verdict(verdict), file=sys.stderr)
    if not verdict["ok"]:
        rc = max(rc, 1)
    if rc == 0:
        # index the record in hand (same object the gate judged) — and
        # only a fully clean run: a floor-failed or regressed run must
        # not become a baseline sample
        try:
            append_record(history_path,
                          dict(record, ingested_unix=round(time.time(), 3)),
                          records=records)
        except OSError as e:
            print(f"bench: history ingest failed ({e})", file=sys.stderr)
            raise SystemExit(2)
    return rc
