"""Perf microscope, read side: ``obs explain`` — the ranked diagnosis.

The sentinel (``obs gate``) turns a regression into exit code 1; this
module turns exit code 1 into a *cause*.  Given an offending run and a
baseline cohort (explicit run dirs, or — for ``obs gate --explain`` —
the comparable runs the history index points at), it diffs every
attribution surface the write side (:mod:`hfrep_tpu.obs.attrib`, PR 12's
flight recorder, PR 2's spans) records:

* **program fingerprints** — HLO digests per compile boundary from the
  ``program_profile`` events + the manifest ``programs`` section: a
  digest the cohort never compiled is a recompile / fusion / lowering
  change, the prime suspect for a step-time move;
* **compile accounting** — ``backend_compiles`` counter and per-name
  ``compile:<step>`` spans: a counter jumping 1 → 9 is a retracing bug,
  not an XLA regression;
* **cost analysis** — per-program ``cost_analysis()`` flops/bytes: the
  same boundary costing +12% flops is a program-content change even
  when the digest alone can't say what moved;
* **dispatch-vs-compute** — the ``attrib/*`` gauges: a dispatch_frac
  up 11 points blames the host loop, not the chip;
* **spans & metrics** — per-name span totals and the headline summary
  numbers, as supporting evidence and context.

Each surface yields findings scored by kind-weight × normalized delta;
the render is one ranked list ("p95 regression co-occurs with 2 new HLO
digests at compile:multi_step; dispatch_frac +11pt"), human or JSON.
Degraded inputs — empty or torn event streams, runs with no manifest,
fingerprint-less runs from jax builds without ``cost_analysis`` — yield
fewer findings and explicit notes, never a crash; a diagnosis with no
attributable surface says so (``attributed: false``) instead of
inventing one.  Stdlib-only, like the whole obs read path.
"""

from __future__ import annotations

import json
import math
import sys
from pathlib import Path
from statistics import median
from typing import Dict, List, Optional

from hfrep_tpu.obs.history import _num
from hfrep_tpu.obs.report import SchemaError, load_events, summarize

#: findings below this score are dropped from the ranked list (noise
#: floor: a 1% span move explains nothing)
MIN_SCORE = 0.2

#: metrics worth naming as regression context, with their direction
_CONTEXT_METRICS = (("steps_per_sec", "up"), ("step_time_p50_s", "down"),
                    ("step_time_p95_s", "down"), ("mfu", "up"),
                    ("memory_high_water_bytes", "down"))


# ------------------------------------------------------------- evidence
def run_evidence(run_dir) -> dict:
    """Everything diffable about one run, degraded-tolerantly: a run
    with no events, no manifest or no fingerprints yields empty tables
    plus a note — the diagnosis then says what it could not see."""
    run_dir = Path(run_dir)
    notes: List[str] = []
    try:
        events = load_events(run_dir)
    except (OSError, SchemaError) as e:
        events = []
        notes.append(f"events unreadable: {e}")
    try:
        manifest = json.loads((run_dir / "run.json").read_text())
    except (OSError, json.JSONDecodeError) as e:
        manifest = {}
        notes.append(f"manifest unreadable: {e}")

    # programs: manifest index ∪ program_profile events (either side may
    # be missing — crashed before the manifest write, or an old run)
    programs: Dict[str, List[dict]] = {}
    for name, entries in (manifest.get("programs") or {}).items():
        if isinstance(entries, list):
            programs[str(name)] = [e for e in entries if isinstance(e, dict)]
    spans: Dict[str, dict] = {}
    compile_spans: Dict[str, dict] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    # a compacted run dir seeds the aggregates of the plain spans and
    # metric samples compaction folded away (first-seen order preserved;
    # names whose records were all pinned arrive as zero placeholders the
    # pinned replay below then fills) — evidence stays identical to a
    # raw-stream replay
    from hfrep_tpu.obs import rollup as _rollup
    eseed = _rollup.evidence_seed(run_dir)
    if eseed:
        spans.update({k: dict(v) for k, v in eseed["spans"].items()})
        gauges.update(eseed["gauges"])
        counters.update(eseed["counters"])
    for rec in events:
        if rec["type"] == "span":
            if rec.get("warmup"):
                continue        # compile-polluted windows explain nothing
            sname = str(rec["name"])
            agg = spans.setdefault(sname, {"n": 0, "total_s": 0.0})
            agg["n"] += 1
            agg["total_s"] += float(rec["dur"])
            if sname.startswith("compile:"):
                c = compile_spans.setdefault(sname, {"n": 0, "total_s": 0.0})
                c["n"] += 1
                c["total_s"] += float(rec["dur"])
        elif rec["type"] == "metric":
            if rec["kind"] == "counter":
                counters[str(rec["name"])] = rec["value"]
            elif rec["kind"] == "gauge":
                gauges[str(rec["name"])] = rec["value"]
    for rec in events:
        if rec["type"] == "event" and rec.get("name") == "program_profile":
            bname = rec.get("program")
            if not bname:
                continue
            entry = {k: rec.get(k) for k in ("hlo_sha256", "hlo_bytes",
                                             "cost", "memory")}
            seen = programs.setdefault(str(bname), [])
            if entry.get("hlo_sha256") is not None and not any(
                    p.get("hlo_sha256") == entry["hlo_sha256"]
                    for p in seen):
                seen.append(entry)
    if not events:
        notes.append("no events parsed (empty or absent stream)")
    if not programs:
        notes.append("no program fingerprints recorded (pre-microscope "
                     "run, or a jax without lowering introspection)")

    s = None
    try:
        s = summarize(run_dir, events=events)
    except (OSError, SchemaError) as e:
        notes.append(f"summary unavailable: {e}")
    return {
        "run_dir": str(run_dir),
        "run_id": (s or {}).get("run_id") or run_dir.name,
        "programs": programs,
        "spans": spans,
        "compile_spans": compile_spans,
        "counters": counters,
        "gauges": gauges,
        "summary": s or {},
        "notes": notes,
    }


def _digests(ev: dict, name: str) -> set:
    return {p.get("hlo_sha256") for p in ev["programs"].get(name, [])
            if p.get("hlo_sha256")}


def _flops(ev: dict, name: str) -> Optional[float]:
    vals = [_num((p.get("cost") or {}).get("flops"))
            for p in ev["programs"].get(name, [])]
    vals = [v for v in vals if v is not None]
    return max(vals) if vals else None


def _cohort_median(values) -> Optional[float]:
    vals = [v for v in (_num(x) for x in values) if v is not None]
    return median(vals) if vals else None


# ------------------------------------------------------------- findings
def _finding(kind: str, score: float, summary: str, **detail) -> dict:
    return {"kind": kind, "score": round(float(score), 4),
            "summary": summary, "detail": detail}


def diagnose(target: dict, cohort: List[dict], top: int = 10) -> dict:
    """Rank every attributable delta between ``target`` (evidence of the
    offending run) and the baseline ``cohort`` (evidence dicts; medians
    / digest unions over it are the baseline)."""
    findings: List[dict] = []
    notes = list(target["notes"])
    for ev in cohort:
        for n in ev["notes"]:
            note = f"cohort {ev['run_id']}: {n}"
            if note not in notes:
                notes.append(note)

    # -- program fingerprints: target digests the cohort never compiled
    cohort_names = set()
    for ev in cohort:
        cohort_names |= set(ev["programs"])
    cohort_has_programs = bool(cohort_names)
    for name in sorted(target["programs"]):
        t_dig = _digests(target, name)
        c_dig = set()
        for ev in cohort:
            c_dig |= _digests(ev, name)
        new = t_dig - c_dig
        if not cohort_has_programs:
            continue            # nothing to diff against; noted below
        if name not in cohort_names and t_dig:
            findings.append(_finding(
                "program", 2.5 + 0.25 * len(t_dig),
                f"{name}: program absent from the baseline cohort "
                f"({len(t_dig)} digest(s)) — a compile boundary the "
                "baseline never had",
                program=name, new_digests=sorted(new)))
            continue
        if new:
            t_fl, c_fl = _flops(target, name), _cohort_median(
                [_flops(ev, name) for ev in cohort])
            fl = ""
            detail = {"program": name, "new_digests": sorted(new),
                      "cohort_digests": len(c_dig)}
            if t_fl is not None and c_fl:
                rel = (t_fl - c_fl) / c_fl
                fl = f" (cost-analysis flops {rel:+.1%})"
                detail["flops"] = t_fl
                detail["flops_baseline"] = c_fl
            # base score sits above the compile-storm ceiling on
            # purpose: when both fire, the changed PROGRAM is the
            # thing to read first (the storm is usually its symptom)
            findings.append(_finding(
                "program", 3.5 + 0.5 * len(new),
                f"{name}: {len(new)} new HLO digest(s) not in the "
                f"baseline cohort{fl} — the program itself changed "
                "(recompile / fusion / lowering delta)",
                **detail))
        if len(t_dig) > 1:
            findings.append(_finding(
                "program", 2.0 + 0.5 * (len(t_dig) - 1),
                f"{name}: {len(t_dig)} distinct digests WITHIN the run "
                "— a mid-run recompile at one boundary",
                program=name, digests=sorted(t_dig)))
    missing = [n for n in sorted(cohort_names)
               if n not in target["programs"]]
    if missing and target["programs"]:
        findings.append(_finding(
            "program", 1.0 + 0.2 * len(missing),
            f"{len(missing)} baseline compile boundar"
            f"{'y' if len(missing) == 1 else 'ies'} absent from the "
            f"offending run: {', '.join(missing[:4])}"
            f"{'…' if len(missing) > 4 else ''}",
            missing=missing))

    # -- compile counts: backend counter + per-name compile spans
    t_bc = _num(target["counters"].get("backend_compiles"))
    c_bc = _cohort_median([ev["counters"].get("backend_compiles")
                           for ev in cohort])
    if t_bc is not None and c_bc is not None and t_bc - c_bc > 2:
        findings.append(_finding(
            "compile", 2.5 + 0.5 * math.log2(max(t_bc - c_bc, 2)),
            f"backend_compiles {int(t_bc)} vs cohort median {int(c_bc)} "
            f"(+{int(t_bc - c_bc)}) — a retracing/recompile storm, not "
            "an XLA slowdown",
            observed=t_bc, baseline=c_bc))
    for name in sorted(target["compile_spans"]):
        t_n = target["compile_spans"][name]["n"]
        c_n = _cohort_median([ev["compile_spans"].get(name, {}).get("n")
                              for ev in cohort])
        if c_n is not None and t_n - c_n >= 1:
            findings.append(_finding(
                "compile", 1.5 + 0.5 * (t_n - c_n),
                f"{name}: {int(t_n)} compile span(s) vs cohort median "
                f"{int(c_n)} — the step recompiled where the baseline "
                "compiled once",
                span=name, observed=t_n, baseline=c_n))

    # -- cost-analysis flops drift on unchanged-name programs
    for name in sorted(target["programs"]):
        t_fl = _flops(target, name)
        c_fl = _cohort_median([_flops(ev, name) for ev in cohort])
        if t_fl is None or not c_fl:
            continue
        rel = (t_fl - c_fl) / c_fl
        if abs(rel) > 0.05:
            findings.append(_finding(
                "cost", 1.5 + 5.0 * abs(rel),
                f"{name}: cost-analysis flops {rel:+.1%} vs cohort "
                f"median ({t_fl:.3g} vs {c_fl:.3g}) — the program is "
                "doing different work",
                program=name, flops=t_fl, flops_baseline=c_fl))

    # -- dispatch-vs-compute attribution
    t_frac = _num(target["gauges"].get("attrib/dispatch_frac"))
    c_frac = _cohort_median([ev["gauges"].get("attrib/dispatch_frac")
                             for ev in cohort])
    if t_frac is not None and c_frac is not None:
        dpt = (t_frac - c_frac) * 100.0
        if dpt > 3.0:
            findings.append(_finding(
                "attrib", 1.5 + 0.15 * dpt,
                f"dispatch_frac {t_frac:.2f} vs {c_frac:.2f} "
                f"({dpt:+.0f}pt) — the HOST share of the step wall grew; "
                "suspect dispatch overhead / python loop, not the chip",
                observed=t_frac, baseline=c_frac))
    for gname, label in (("attrib/dispatch_ms", "host-dispatch"),
                         ("attrib/compute_ms", "device-compute")):
        t_v = _num(target["gauges"].get(gname))
        c_v = _cohort_median([ev["gauges"].get(gname) for ev in cohort])
        if t_v is None or not c_v:
            continue
        rel = (t_v - c_v) / c_v
        if rel > 0.15:
            findings.append(_finding(
                "attrib", 0.8 + 2.0 * rel,
                f"{gname} {t_v:.3g} vs {c_v:.3g} ({rel:+.1%}) — the "
                f"{label} share of the boundary window grew",
                gauge=gname, observed=t_v, baseline=c_v))

    # -- wall-clock ledger category deltas (ISSUE 18), gated on
    # IDENTICAL program fingerprints: when the target compiled exactly
    # the digests the cohort compiled (same boundaries, same HLO), the
    # regression cannot be "the program changed" — the wall clock moved
    # between categories instead, and the cumulative ``timeline/*_frac``
    # gauges say from where to where.  On differing programs the
    # program/compile findings above own the diagnosis and a category
    # delta would only restate their symptom, so the section stays
    # silent there (and on pre-ledger runs without the gauges).
    same_programs = bool(target["programs"]) and cohort_has_programs \
        and set(target["programs"]) == cohort_names
    if same_programs:
        for name in cohort_names:
            c_dig = set()
            for ev in cohort:
                c_dig |= _digests(ev, name)
            if _digests(target, name) != c_dig:
                same_programs = False
                break
    if same_programs:
        from hfrep_tpu.obs.timeline import CATEGORIES
        for cat in CATEGORIES:
            gname = f"timeline/{cat}_frac"
            t_v = _num(target["gauges"].get(gname))
            c_v = _cohort_median([ev["gauges"].get(gname)
                                  for ev in cohort])
            if t_v is None or c_v is None:
                continue
            dpt = (t_v - c_v) * 100.0
            # device_compute is the one GOOD category: it shrinking is
            # the symptom the overhead categories' growth explains
            if cat == "device_compute" or dpt <= 2.0:
                continue
            findings.append(_finding(
                "timeline", 1.2 + 0.12 * dpt,
                f"{gname} {t_v:.3f} vs cohort {c_v:.3f} ({dpt:+.0f}pt) "
                f"on an UNCHANGED program — the wall clock moved into "
                f"{cat}, not into different device work",
                gauge=gname, observed=t_v, baseline=c_v))
        t_ov = _num(target["gauges"].get("timeline/overlap_frac"))
        c_ov = _cohort_median([ev["gauges"].get("timeline/overlap_frac")
                               for ev in cohort])
        if t_ov is not None and c_ov is not None \
                and (c_ov - t_ov) * 100.0 > 5.0:
            findings.append(_finding(
                "timeline", 1.2 + 0.12 * (c_ov - t_ov) * 100.0,
                f"timeline/overlap_frac {t_ov:.3f} vs cohort {c_ov:.3f} "
                f"({(t_ov - c_ov) * 100.0:+.0f}pt) — less host work is "
                "hidden behind device execution than the baseline "
                "managed (pipelining regressed)",
                gauge="timeline/overlap_frac", observed=t_ov,
                baseline=c_ov))

    # -- span movers (supporting evidence; per-occurrence mean so a run
    # with more blocks isn't "slower" by volume alone)
    for name in sorted(target["spans"]):
        if name.startswith("compile:"):
            continue            # already attributed above
        t_s = target["spans"][name]
        t_mean = t_s["total_s"] / t_s["n"] if t_s["n"] else None
        c_means = []
        for ev in cohort:
            c = ev["spans"].get(name)
            if c and c["n"]:
                c_means.append(c["total_s"] / c["n"])
        c_mean = _cohort_median(c_means)
        if t_mean is None or not c_mean:
            continue
        rel = (t_mean - c_mean) / c_mean
        if rel > 0.10:
            findings.append(_finding(
                "span", min(0.5 + 1.5 * rel, 2.0),
                f"span {name}: mean {t_mean * 1e3:.3g} ms vs cohort "
                f"{c_mean * 1e3:.3g} ms ({rel:+.1%})",
                span=name, observed_s=t_mean, baseline_s=c_mean))

    # -- headline metric context (ranked low: it restates the gate)
    t_sum = target["summary"]
    for metric, direction in _CONTEXT_METRICS:
        t_v = _num(t_sum.get(metric))
        c_v = _cohort_median([ev["summary"].get(metric) for ev in cohort])
        if t_v is None or not c_v:
            continue
        rel = (t_v - c_v) / abs(c_v)
        worse = rel < -0.02 if direction == "up" else rel > 0.02
        if worse:
            findings.append(_finding(
                "metric", min(0.3 + abs(rel), 1.0),
                f"{metric} {t_v:.6g} vs cohort median {c_v:.6g} "
                f"({rel:+.1%})",
                metric=metric, observed=t_v, baseline=c_v))

    findings = [f for f in findings if f["score"] >= MIN_SCORE]
    findings.sort(key=lambda f: -f["score"])
    findings = findings[: max(1, int(top))]
    for i, f in enumerate(findings, 1):
        f["rank"] = i
    attributed = any(f["kind"] in ("program", "compile", "cost", "attrib",
                                   "timeline")
                     for f in findings)
    return {
        "v": 1,
        "target": {"run_id": target["run_id"],
                   "run_dir": target["run_dir"]},
        "cohort": [{"run_id": ev["run_id"], "run_dir": ev["run_dir"]}
                   for ev in cohort],
        "attributed": attributed,
        "findings": findings,
        "notes": notes,
    }


#: the committed 0-findings snapshot `python -m hfrep_tpu.analysis audit
#: --format sarif` maintains; results carry ``properties.boundary``
_AUDIT_SNAPSHOT = (Path(__file__).resolve().parents[1]
                   / "analysis" / "audit_snapshot.sarif")


def annotate_static_audit(doc: dict, snapshot_path=None) -> dict:
    """When a regressed program boundary also carries an OPEN finding in
    the committed static program audit (JPX rules over the traced jaxpr/
    HLO), add a one-line pointer: a known donation/precision/host-sync
    defect at the same boundary is usually the cheaper explanation than
    anything runtime telemetry alone can offer.  Joins the diagnosis's
    program-kind findings (``detail.program``, the runtime boundary
    vocabulary) against the snapshot results' ``properties.boundary``
    (the registry label minus its ``@variant``).  Stdlib json only; a
    missing or malformed snapshot annotates nothing."""
    path = Path(snapshot_path) if snapshot_path else _AUDIT_SNAPSHOT
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return doc
    open_rules: Dict[str, set] = {}
    for run in data.get("runs", []) if isinstance(data, dict) else []:
        for res in run.get("results", []):
            b = (res.get("properties") or {}).get("boundary")
            if b:
                open_rules.setdefault(str(b), set()).add(
                    str(res.get("ruleId") or "?"))
    if not open_rules:
        return doc
    hit: Dict[str, set] = {}
    for f in doc.get("findings", []):
        if f.get("kind") != "program":
            continue
        prog = str((f.get("detail") or {}).get("program") or "")
        for b, rules in open_rules.items():
            # serve boundaries profile per batch bucket (serve:replicate:b32)
            if prog == b or prog.startswith(b + ":"):
                hit.setdefault(b, set()).update(rules)
    for b in sorted(hit):
        doc.setdefault("notes", []).append(
            f"static audit: {b} has open {', '.join(sorted(hit[b]))} "
            f"finding(s) in {path.name} — `python -m hfrep_tpu.analysis "
            "audit` before chasing the runtime delta")
    return doc


def explain_runs(cohort_dirs, target_dir, top: int = 10) -> dict:
    """``obs explain RUN_A RUN_B``'s engine: diagnosis of ``target_dir``
    against the baseline cohort (one or more run dirs)."""
    target = run_evidence(target_dir)
    cohort = [run_evidence(d) for d in cohort_dirs]
    return annotate_static_audit(diagnose(target, cohort, top=top))


# ------------------------------------------------------------- rendering
_KIND_GLYPH = {"program": "program", "compile": "compile", "cost": "cost",
               "attrib": "attrib", "span": "span", "metric": "metric"}


def render_diagnosis(doc: dict) -> str:
    cohort = ", ".join(c["run_id"] for c in doc["cohort"]) or "(empty)"
    head = (f"obs explain — {doc['target']['run_id']} vs cohort of "
            f"{len(doc['cohort'])} ({cohort})")
    lines = [head]
    if not doc["findings"]:
        lines.append("  no attributable deltas found")
    for f in doc["findings"]:
        glyph = _KIND_GLYPH.get(f["kind"], f["kind"])[:7]
        lines.append(f"  {f['rank']:2d}. [{glyph:7s}] {f['summary']}")
    if not doc["attributed"]:
        lines.append(
            "UNATTRIBUTED: no program-fingerprint, compile-count, "
            "cost-analysis or dispatch-attribution delta survived the "
            "noise floor — the committed evidence cannot localize this "
            "regression (see notes)")
    for n in doc["notes"]:
        lines.append(f"  note: {n}")
    return "\n".join(lines)


# --------------------------------------------- gate --explain integration
def resolve_run_dir(recorded: str, history_path=None) -> Optional[Path]:
    """A history record's ``run_dir`` string → an existing directory, or
    None.  Records store whatever path ingest saw — absolute, cwd-
    relative (the committed fixtures are repo-relative), or a path on a
    host this machine is not — so try as-is, then relative to the repo
    root, then relative to the history file's parent."""
    if not recorded:
        return None
    candidates = [Path(recorded)]
    repo_root = Path(__file__).resolve().parents[2]
    candidates.append(repo_root / recorded)
    if history_path is not None:
        candidates.append(Path(history_path).resolve().parent / recorded)
    for c in candidates:
        if c.is_dir() and ((c / "events.jsonl").exists()
                           or (c / "run.json").exists()):
            return c
    return None


def explain_gate_failure(run_dir, record: dict, records: List[dict],
                         history_path=None, top: int = 10,
                         window: int = 8) -> dict:
    """The ``obs gate --explain`` tail: resolve the baseline cohort —
    the last ``window`` comparable history records whose run dirs still
    exist on disk — and diagnose the offending run against it.  With no
    resolvable cohort the diagnosis says exactly what was missing
    instead of guessing."""
    key = record.get("key") or {}
    cohort_dirs: List[Path] = []
    unresolved = 0
    for rec in reversed(records):
        if rec.get("key") != key:
            continue
        if (rec.get("run_id") == record.get("run_id")
                and rec.get("created_unix") == record.get("created_unix")):
            continue
        d = resolve_run_dir(str(rec.get("run_dir") or ""), history_path)
        if d is None:
            unresolved += 1
            continue
        if d not in cohort_dirs:
            cohort_dirs.append(d)
        if len(cohort_dirs) >= window:
            break
    doc = explain_runs(cohort_dirs, run_dir, top=top)
    if unresolved:
        doc["notes"].append(
            f"{unresolved} comparable history record(s) reference run "
            "dirs not present on this machine (back-filled or foreign-"
            "host records carry no diffable telemetry)")
    if not cohort_dirs:
        doc["attributed"] = False
        doc["notes"].append(
            "no baseline cohort run dir resolvable from the history "
            "index — fingerprint/attrib diffs need the baseline runs' "
            "telemetry on disk")
    return doc


# ------------------------------------------------- history-series report
def history_report(records: List[dict], key: Optional[dict] = None) -> dict:
    """What the committed history STORE alone can and cannot attribute:
    per-metric series (values, worst drop, OLS slope) plus an explicit
    evidence inventory (how many records carry compile counters /
    memory / run dirs with live telemetry).  This is the honest tool
    for the BENCH_r01–r05 question — back-filled stdout records carry
    rates but no fingerprints, and this says so with numbers."""
    from hfrep_tpu.obs.regress import trend_slope

    if key is not None:
        records = [r for r in records if r.get("key") == key]
    by_metric: Dict[str, List[float]] = {}
    for rec in records:
        for m, v in (rec.get("metrics") or {}).items():
            v = _num(v)
            if v is not None:
                by_metric.setdefault(m, []).append(float(v))
    series = {}
    for m, vals in sorted(by_metric.items()):
        base = median(vals)
        slope = trend_slope(vals)
        series[m] = {
            "n": len(vals), "values": vals,
            "median": round(base, 9),
            "min": min(vals), "max": max(vals),
            "slope_per_run": (round(slope, 9) if slope is not None
                              else None),
            "spread_frac": (round((max(vals) - min(vals)) / abs(base), 6)
                            if base else None),
        }
    n = len(records)
    evidence = {
        "records": n,
        "with_backend_compiles": sum(
            1 for r in records
            if _num((r.get("metrics") or {}).get("backend_compiles"))
            is not None),
        "with_memory": sum(
            1 for r in records
            if _num((r.get("metrics") or {}).get(
                "memory_high_water_bytes")) is not None),
        "with_step_percentiles": sum(
            1 for r in records
            if _num((r.get("metrics") or {}).get("step_time_p50_s"))
            is not None),
        "with_resolvable_run_dir": sum(
            1 for r in records
            if resolve_run_dir(str(r.get("run_dir") or "")) is not None),
    }
    return {"v": 1, "key": key, "series": series, "evidence": evidence}


def render_history_report(doc: dict) -> str:
    ev = doc["evidence"]
    lines = [f"history attribution inventory — {ev['records']} record(s)"]
    lines.append(
        f"  evidence: backend_compiles on {ev['with_backend_compiles']}, "
        f"memory on {ev['with_memory']}, step percentiles on "
        f"{ev['with_step_percentiles']}, live run dirs for "
        f"{ev['with_resolvable_run_dir']}")
    for m, s in doc["series"].items():
        slope = ("-" if s["slope_per_run"] is None
                 else f"{s['slope_per_run']:+.4g}/run")
        lines.append(f"  {m:34s} n={s['n']:2d} median {s['median']:.6g} "
                     f"range [{s['min']:.6g}, {s['max']:.6g}] "
                     f"slope {slope}")
    return "\n".join(lines)


# -------------------------------------------------------------- self-test
def fixture_dir() -> Path:
    """The committed two-run explain fixture: a base run and a run with
    a planted regression whose diagnosis is known (new HLO digest at
    ``compile:multi_step``, backend_compiles 1 → 9, dispatch_frac
    +11pt)."""
    from hfrep_tpu.obs.report import fixture_dir as _fx
    return _fx() / "explain"


def self_test() -> int:
    """CI gate for the diagnosis loop (``obs explain --self-test``,
    env-stripped in ``tools/check.sh`` beside the gate self-test): the
    committed planted regression must produce a ranked diagnosis naming
    the planted causes in a sane order, a base-vs-base diff must stay
    silent, and the JSON document must round-trip.  Pure-JSON result on
    stdout; diagnostics on stderr."""
    fx = fixture_dir()
    try:
        base, bad = fx / "base", fx / "regressed"
        # committed fixtures must be whole — strict parse both streams
        for d in (base, bad):
            if not load_events(d, strict=True):
                raise SchemaError(f"{d}: empty fixture stream")
        doc = explain_runs([base], bad)
        if not doc["findings"]:
            raise SchemaError("planted regression produced no findings")
        if not doc["attributed"]:
            raise SchemaError("planted regression not attributed")
        kinds = {f["kind"] for f in doc["findings"]}
        for want in ("program", "compile", "attrib"):
            if want not in kinds:
                raise SchemaError(
                    f"planted {want} cause missing from diagnosis "
                    f"(kinds: {sorted(kinds)})")
        top_f = doc["findings"][0]
        if top_f["kind"] != "program" \
                or "compile:multi_step" not in top_f["summary"]:
            raise SchemaError(
                "top-ranked finding is not the planted program-"
                f"fingerprint delta: {top_f['summary']!r}")
        scores = [f["score"] for f in doc["findings"]]
        if scores != sorted(scores, reverse=True):
            raise SchemaError("findings not ranked by score")
        if "attrib/dispatch_frac" not in json.dumps(doc) and not any(
                "dispatch_frac" in f["summary"] for f in doc["findings"]):
            raise SchemaError("planted dispatch_frac delta not named")
        # no false positives: a run diffed against itself is silent
        clean = explain_runs([base], base)
        if any(f["kind"] in ("program", "compile", "cost", "attrib")
               for f in clean["findings"]):
            raise SchemaError(
                "base-vs-base diagnosis invented attributed causes: "
                f"{[f['summary'] for f in clean['findings']]}")
        # the document round-trips as one JSON object
        round_tripped = json.loads(json.dumps(doc, default=str))
        if round_tripped["findings"][0]["rank"] != 1:
            raise SchemaError("diagnosis JSON lost its ranking")
    except (OSError, json.JSONDecodeError, SchemaError, KeyError,
            ValueError) as e:
        print(f"obs explain self-test FAILED: {e}", file=sys.stderr)
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print("obs explain self-test OK", file=sys.stderr)
    print(json.dumps({
        "ok": True,
        "findings": len(doc["findings"]),
        "attributed": doc["attributed"],
        "top": {"kind": top_f["kind"], "summary": top_f["summary"]},
        "kinds": sorted(kinds),
    }))
    return 0
