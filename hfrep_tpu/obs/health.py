"""In-graph training health: grad/update/param norms + nonfinite counts.

The paper's failure mode is silent: WGAN-GP critic losses go NaN and the
host loop keeps dispatching — nothing inside the jitted scans measures
gradient or weight health, so divergence is discovered at evaluation
time, thousands of epochs too late (the flight-recorder gap, ISSUE 12).
This module closes it *without changing a single compiled program when
off and without adding a single device→host sync when on*:

* the step builders (:mod:`hfrep_tpu.train.steps`,
  :mod:`hfrep_tpu.replication.engine`) consult :func:`active` at BUILD
  time.  Off (the default): the traced graph is the literal pre-health
  program — the fp32 jaxpr pins hold by construction.  On: the steps
  additionally compute global grad-norm, update-norm, param-norm and a
  nonfinite element count *inside the existing scan carries* and return
  them as extra metric/trace outputs.  Those outputs are pure functions
  of values the step already computes, so the training trajectory is
  bit-identical either way (pinned by ``tests/test_obs_health.py``);
* the values reach the host only at the boundaries the drives already
  sync at (the trainer's per-block metrics ``device_get``, the chunked
  AE engine's continue/stop scalar) and surface as ``health/*`` gauges;
* :attr:`HealthConfig.abort_on_nonfinite` arms the tripwire: a nonfinite
  count observed at a boundary raises a typed :class:`NumericFault`
  after dumping the offending carry + metrics to an atomic forensic
  directory (``numeric_fault_<epoch>/`` via ``write_atomic``) — the
  crash-forensics layer (:mod:`hfrep_tpu.obs.crash`) then bundles the
  event tail around it.

Activation: :func:`configure` programmatically, or the ``HFREP_HEALTH``
env var — ``1``/``on`` enables measurement, ``abort`` additionally arms
the tripwire (read once per process, like ``HFREP_FAULTS``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

ENV_HEALTH = "HFREP_HEALTH"

#: the gauge vocabulary this layer emits (every name has an explicit
#: ``regress.DEFAULT_THRESHOLDS`` row — the HF001 contract)
GAUGES = (
    "health/g_grad_norm",
    "health/d_grad_norm",
    "health/update_norm",
    "health/param_norm",
    "health/nonfinite",
    "health/ae_grad_norm",
    "health/ae_param_norm",
    "health/ae_nonfinite",
)

#: metric-dict keys the GAN steps add when health is on (the trainer
#: maps ``health_<x>`` -> the ``health/<x>`` gauge at block boundaries)
STEP_KEYS = ("health_g_grad_norm", "health_d_grad_norm",
             "health_update_norm", "health_param_norm", "health_nonfinite")


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """The flight recorder's in-graph health knobs."""

    #: measure grad/update/param norms + nonfinite counts in-graph
    enabled: bool = True
    #: a nonfinite count observed at a boundary raises :class:`NumericFault`
    #: (after the forensic dump) instead of training on
    abort_on_nonfinite: bool = False
    #: forensic dump location; None = ``<obs run_dir>/`` when telemetry is
    #: on, else the drive's checkpoint/resume dir, else no dump
    dump_dir: Optional[str] = None


class NumericFault(RuntimeError):
    """Training produced nonfinite gradients/weights and the health
    tripwire is armed.  Carries the boundary site, the epoch and the
    forensic dump path (when one was written) — the crash-forensics
    bundle picks these up via ``__dict__``."""

    def __init__(self, site: str, epoch: Optional[int] = None,
                 nonfinite: Optional[float] = None,
                 dump: Optional[str] = None,
                 detail: Optional[str] = None):
        self.site, self.epoch, self.nonfinite, self.dump = (
            site, epoch, nonfinite, dump)
        msg = f"nonfinite values detected at {site} boundary"
        if epoch is not None:
            msg += f" (epoch {epoch})"
        if nonfinite:
            msg += f": {int(nonfinite)} nonfinite element(s)"
        if detail:
            msg += f" [{detail}]"
        if dump:
            msg += f"; forensic dump at {dump}"
        super().__init__(msg)


_active: Optional[HealthConfig] = None
_env_consumed = False


def configure(cfg: Optional[HealthConfig]) -> Optional[HealthConfig]:
    """Install (or clear, with None) the process-wide health config."""
    global _active, _env_consumed
    _active, _env_consumed = cfg, True
    return cfg


def active() -> Optional[HealthConfig]:
    """The installed config, else one parsed from ``HFREP_HEALTH`` (read
    once per process); None when health telemetry is off — the builders'
    one branch point."""
    global _active, _env_consumed
    if _active is None and not _env_consumed:
        spec = (os.environ.get(ENV_HEALTH) or "").strip().lower()
        if spec and spec not in ("0", "off", "false"):
            _active = HealthConfig(
                enabled=True, abort_on_nonfinite=(spec == "abort"))
        _env_consumed = True
    if _active is not None and not _active.enabled:
        return None
    return _active


# ------------------------------------------------------- in-graph helpers
def tree_sq_norm(tree):
    """Σ‖leaf‖² over a pytree, accumulated in float32 (identity cast on
    fp32 inputs — the precision-policy discipline)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def tree_norm(tree):
    """Global L2 norm of a pytree (float32)."""
    import jax.numpy as jnp
    return jnp.sqrt(tree_sq_norm(tree))


def tree_nonfinite(tree):
    """Count of non-finite elements across a pytree, as float32 (floats
    ride the existing metric plumbing; the count is exact well past any
    realistic parameter count)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            total = total + jnp.sum(
                (~jnp.isfinite(leaf)).astype(jnp.float32))
    return total


def tree_update_sq_norm(old_tree, new_tree):
    """Σ‖new − old‖² over two same-structure pytrees (float32) — the
    per-boundary update magnitude."""
    import jax
    import jax.numpy as jnp

    old_l = jax.tree_util.tree_leaves(old_tree)
    new_l = jax.tree_util.tree_leaves(new_tree)
    total = jnp.zeros((), jnp.float32)
    for o, n in zip(old_l, new_l):
        total = total + jnp.sum(jnp.square(
            n.astype(jnp.float32) - o.astype(jnp.float32)))
    return total


# -------------------------------------------------------------- forensics
def dump_forensics(dump_dir, carry, detail: Optional[dict] = None,
                   name: str = "numeric_fault") -> Optional[str]:
    """Persist the offending carry pytree (+ a JSON detail document)
    atomically under ``dump_dir/<name>``; returns the dump path, or None
    when nothing could be written.  Best-effort by design: forensics
    must never mask the fault they describe."""
    if dump_dir is None:
        return None
    try:
        import json
        from pathlib import Path

        import jax
        import numpy as np

        from hfrep_tpu.utils import checkpoint as ckpt

        leaves = [np.asarray(x) for x in
                  jax.device_get(jax.tree_util.tree_leaves(carry))]
        doc = json.dumps(detail or {}, default=str, indent=2)

        def writer(tmp: Path) -> None:
            np.savez(tmp / "carry.npz",
                     **{f"leaf_{i}": v for i, v in enumerate(leaves)})
            (tmp / "detail.json").write_text(doc)

        path = Path(dump_dir) / name
        ckpt.write_atomic(path, writer,
                          metadata={"kind": "numeric_fault_dump",
                                    "n_leaves": len(leaves)})
        return str(path)
    except Exception:
        return None


def resolve_dump_dir(cfg: HealthConfig,
                     fallback: Optional[str] = None) -> Optional[str]:
    """Where a forensic dump should land: the configured dir, else the
    active obs run dir, else the caller's fallback (checkpoint/resume
    dir), else nowhere."""
    if cfg.dump_dir:
        return cfg.dump_dir
    try:
        from hfrep_tpu.obs import get_obs
        obs = get_obs()
        if obs.enabled:
            return str(obs.run_dir)
    except Exception:
        pass
    return fallback
