"""``python -m hfrep_tpu.obs`` entry point (report CLI)."""

from __future__ import annotations

import sys

from hfrep_tpu.obs.report import main

sys.exit(main())
