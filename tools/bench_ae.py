"""AE replication hot-path probe: full-scan vs chunked-exit vs batched
multi-dataset sweep wall clock.

The paper's headline experiment (the 21-latent-dim AE sweep with
Keras-faithful EarlyStopping, ``autoencoder_v4.ipynb`` cells 5-33) used
to pay the full ≤1000-epoch ``lax.scan`` with post-stop updates merely
masked — ~94% dead FLOPs on a run that converges at epoch ~60.  This
probe measures what the chunked early-exit drive
(:func:`hfrep_tpu.replication.engine.sweep_autoencoders_chunked`) and
the padded cross-dataset fabric
(:func:`~hfrep_tpu.replication.engine.sweep_autoencoders_multi`) buy on
this host, and SELF-CHECKS the win: on the early-exit fixture — every
lane stops before ``epochs/4`` — the chunked drive must be >=2x faster
than the monolithic scan, or the probe exits 1.

The early-exit fixture pins the stop epoch *deterministically*: with
``lr=0`` the validation loss never improves after epoch 1, so Keras
EarlyStopping fires at exactly ``patience + 1`` on every lane — the
dispatch saving under test is a property of the drive, not of how fast
some synthetic dataset happens to converge.  A second, genuinely
*learning* fixture (real lr, low-rank data) reports realistic
epochs-saved numbers alongside, un-asserted.

Prints ONE JSON line.  Exit 0 = self-check passed, 1 = the chunked
drive lost its win (or a history regression), 2 = tooling failure.

Telemetry: with ``HFREP_OBS_DIR=<dir>`` every measurement lands in an
obs run dir (``bench`` spans, ``bench/ae_*`` gauges, ``ae/epochs_saved``
/ ``ae/lanes_stopped`` via
:func:`~hfrep_tpu.replication.engine.emit_chunk_stats`); with
``HFREP_HISTORY`` on top — or the repo-default store
(``hfrep_tpu.obs.history.default_store``) — the run gates against the
rolling median/MAD baseline and auto-ingests on pass, exactly like
``bench.py``.

``--self-test`` shrinks every shape so the whole probe (including the
>=2x assertion) runs in seconds on CPU — wired into ``tools/check.sh``
and tier-1 so the probe cannot rot.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

if __name__ == "__main__":                     # `python tools/bench_ae.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu.obs import timeline
import hfrep_tpu.obs as obs_pkg
from hfrep_tpu.config import AEConfig
from hfrep_tpu.core import scaler as mm
from hfrep_tpu.replication import engine as ae

#: the self-check floor the acceptance pins: all lanes stopping before
#: epochs/4 must make the chunked drive at least this much faster
MIN_SPEEDUP = 2.0


def synth_panel(seed: int, rows: int, feats: int, rank: int = 3) -> jnp.ndarray:
    """Low-rank scaled panel — structure for the learning fixture, and a
    deterministic input for the lr=0 one."""
    g = np.random.default_rng(seed)
    z = g.normal(size=(rows, rank))
    x = (z @ g.normal(size=(rank, feats))
         + 0.05 * g.normal(size=(rows, feats))).astype(np.float32) * 0.02
    _, scaled = mm.fit_transform(jnp.asarray(x))
    return scaled


def _block(x) -> None:
    jax.block_until_ready(x)


def time_monolithic(key, xs, cfg, latent_dims, repeats: int = 1) -> float:
    """Wall clock of the full-``epochs`` vmapped sweep (one warmed,
    jitted program — compile excluded, like every bench here).
    ``repeats > 1`` takes the min — the standard noise-robust wall-clock
    estimator; the self-test's tiny single-shot timings otherwise flake
    under host load on a shared CI machine."""
    fn = jax.jit(lambda k: ae.sweep_autoencoders(k, xs, cfg, latent_dims))
    _block(fn(key).params)                        # compile + warm
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = timeline.clock()
        _block(fn(key).params)
        best = min(best, timeline.clock() - t0)
    return best


def time_chunked(key, xs, cfg, latent_dims, repeats: int = 1):
    """Wall clock of the chunked early-exit drive (chunk program warmed
    by a first full drive; the timed drive pays dispatches + the one
    scalar sync per chunk, which IS the mechanism under test).  Min over
    ``repeats`` like :func:`time_monolithic`; the drive is deterministic,
    so res/stats are identical across repeats."""
    ae.sweep_autoencoders_chunked(key, xs, cfg, latent_dims)
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = timeline.clock()
        res, stats = ae.sweep_autoencoders_chunked(key, xs, cfg, latent_dims)
        _block(res.params)
        best = min(best, timeline.clock() - t0)
    return best, res, stats


def time_multi(key, x_stack, n_rows, cfg, latent_dims):
    """Batched (one (K+1)xL-lane program) vs serial (per-dataset padded
    sweeps) wall clock for the cross-dataset fabric."""
    ae.sweep_autoencoders_multi(key, x_stack, n_rows, cfg, latent_dims)
    t0 = timeline.clock()
    res, stats = ae.sweep_autoencoders_multi(key, x_stack, n_rows, cfg,
                                             latent_dims)
    _block(res.params)
    batched = timeline.clock() - t0

    dkeys = jax.random.split(key, x_stack.shape[0])
    for d in range(x_stack.shape[0]):             # warm the serial unit
        ae.sweep_autoencoders_padded(dkeys[d], x_stack[d], n_rows[d], cfg,
                                     latent_dims)
    t0 = timeline.clock()
    for d in range(x_stack.shape[0]):
        r, _ = ae.sweep_autoencoders_padded(dkeys[d], x_stack[d], n_rows[d],
                                            cfg, latent_dims)
        _block(r.params)
    serial = timeline.clock() - t0
    return batched, serial, stats


def run_probe(obs, self_test: bool) -> int:
    if self_test:
        # small enough for seconds on CPU, big enough that per-epoch
        # work (not dispatch overhead) dominates the monolithic scan —
        # measured ~7x at this shape, comfortably above the 2x floor
        # (360 full-scan epochs against an exit in the first 30-epoch
        # chunk keeps the structural margin wide enough that host-load
        # noise on a shared CI machine cannot eat it)
        rows, feats, latents = 120, 16, list(range(1, 9))
        epochs, chunk = 360, 30
        learn_epochs = 60
    else:
        rows, feats, latents = 167, 22, list(range(1, 22))
        epochs, chunk = 400, 50
        learn_epochs = 200
    base = AEConfig(n_factors=feats, latent_dim=max(latents), epochs=epochs,
                    batch_size=48, patience=5, seed=0, chunk_epochs=chunk)
    # annotate from the SAME values the measurements run with, so the
    # history key's shape signature can never drift from the shape
    # actually benchmarked (the bench.py rule)
    obs.annotate(config={
        "model": {"family": "ae_sweep", "window": rows, "features": feats,
                  "hidden": max(latents)},
        "train": {"batch_size": base.batch_size}})
    xs = synth_panel(7, rows, feats)
    key = jax.random.PRNGKey(0)

    # --- early-exit fixture: lr=0 pins the stop at patience+1 << epochs/4
    # Self-test timings are single-digit milliseconds: best-of-5 keeps a
    # loaded CI host from flaking the >=2x floor (chip-shape runs stay
    # single-shot — their programs are long enough to swamp the noise).
    repeats = 5 if self_test else 1
    early = dataclasses.replace(base, lr=0.0)
    full_s = time_monolithic(key, xs, early, latents, repeats=repeats)
    chunked_s, res, stats = time_chunked(key, xs, early, latents,
                                         repeats=repeats)
    obs.record_span("bench", full_s, steps=epochs * len(latents),
                    synced=True, config="ae_full_scan")
    obs.record_span("bench", chunked_s,
                    steps=stats.epochs_dispatched * len(latents),
                    synced=True, config="ae_chunked_exit")
    ae.emit_chunk_stats(stats)
    speedup = full_s / chunked_s if chunked_s > 0 else float("inf")
    stop_max = int(np.asarray(res.stop_epoch).max())

    # --- learning fixture: realistic epochs-saved at a real lr
    learn = dataclasses.replace(base, epochs=learn_epochs, patience=3)
    _, _, learn_stats = time_chunked(key, xs, learn, latents)

    # --- cross-dataset fabric: real + 2 padded variants, one program
    x_stack, n_rows = ae.stack_padded(
        [xs, xs[: rows - rows // 6], xs[: rows - rows // 4]])
    multi_batched_s, multi_serial_s, multi_stats = time_multi(
        key, x_stack, n_rows, early, latents)
    obs.record_span("bench", multi_batched_s,
                    steps=multi_stats.epochs_dispatched * multi_stats.lanes,
                    synced=True, config="ae_multi_batched")
    multi_speedup = (multi_serial_s / multi_batched_s
                     if multi_batched_s > 0 else float("inf"))

    # --- self-check: the acceptance floor
    problems = []
    if stop_max >= epochs // 4:
        problems.append(f"fixture lanes stopped at {stop_max}, "
                        f"not before epochs/4 = {epochs // 4}")
    if stats.chunks_dispatched >= -(-epochs // chunk):
        problems.append(f"no early exit: {stats.chunks_dispatched} chunks "
                        f"dispatched of {-(-epochs // chunk)}")
    if speedup < MIN_SPEEDUP:
        problems.append(f"chunked speedup {speedup:.2f}x < {MIN_SPEEDUP}x")

    epochs_per_sec = (stats.epochs_dispatched * len(latents) / chunked_s
                      if chunked_s > 0 else float("nan"))
    print(json.dumps({
        "metric": "ae_sweep_chunk_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "full_scan_s": round(full_s, 4),
        "chunked_exit_s": round(chunked_s, 4),
        "epochs_saved": stats.epochs_saved,
        "epochs_saved_learning": learn_stats.epochs_saved,
        "lanes": stats.lanes,
        "lanes_stopped": stats.lanes_stopped,
        "stop_epoch_max": stop_max,
        "epochs_per_sec": round(epochs_per_sec, 3),
        "multi_batched_s": round(multi_batched_s, 4),
        "multi_serial_s": round(multi_serial_s, 4),
        "multi_speedup": round(multi_speedup, 3),
        "self_check": "ok" if not problems else "; ".join(problems),
        "self_test": bool(self_test),
    }))

    for name, value in (("ae_chunk_speedup", speedup),
                        ("ae_full_scan_s", full_s),
                        ("ae_chunked_exit_s", chunked_s),
                        ("ae_epochs_per_sec", epochs_per_sec),
                        ("ae_multi_batched_s", multi_batched_s),
                        ("ae_multi_serial_s", multi_serial_s),
                        ("ae_multi_speedup", multi_speedup)):
        if np.isfinite(value):
            obs.gauge(f"bench/{name}").set(float(value))
    obs.gauge("ae/epochs_saved_learning").set(
        int(learn_stats.epochs_saved), epochs_total=int(learn_stats.epochs_total))
    obs.memory_snapshot(phase="bench_ae_end")

    if problems:
        print(f"bench_ae: SELF-CHECK FAILED: {'; '.join(problems)}",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_ae",
        description="AE chunked early-exit + multi-dataset sweep probe")
    ap.add_argument("--self-test", action="store_true",
                    help="tiny shapes: the full probe incl. the >=2x "
                         "assertion in seconds (the CI fast path)")
    args = ap.parse_args(argv)

    obs_dir = os.environ.get("HFREP_OBS_DIR")
    with obs_pkg.session_or_off(obs_dir, "bench_ae",
                                command="bench_ae") as obs:
        if obs_dir and not obs.enabled:
            obs_dir = None                 # degraded: nothing to gate below
        rc = run_probe(obs, args.self_test)
    from hfrep_tpu.obs import history as hist_mod
    hist = hist_mod.resolve_history(obs_dir)
    if obs_dir and hist:
        rc = hist_mod.gate_and_ingest(obs_dir, hist, rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
