"""On-chip soak: the window-sharded TRAINER path at W=672 with mid-run resume.

Round-3 verdict: sp training was API-only — no checkpoints, resume,
nan-guard, logging, or steps/sec (VERDICT r3 weak-1).  This drives the
round-4 wiring end to end on the real chip at the suite's own
long-context shape (W=672 = 4x the production window — a window the
reference's single-device serial LSTM never reaches,
``GAN/MTSS_WGAN_GP.py:254-292`` trains W=48):

* `GanTrainer` on a ``('sp',)`` mesh (1 real device here: the pipeline
  degenerates to one chunk but runs the identical code path — shard_map,
  carry injection kernels, scanned multi-epoch blocks; multi-chip
  trajectory equivalence is pinned on the virtual mesh,
  tests/test_train.py::TestMeshTrainer);
* `lstm_backend='auto'` resolves to the pallas carry-injection kernels;
* periodic checkpoints, then a SECOND trainer restores the MIDPOINT
  checkpoint and finishes the schedule — final params must match the
  uninterrupted run bitwise (the key stream is checkpointed state).

Usage:  python tools/chip_sp_trainer_soak.py [epochs] (default 100)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from hfrep_tpu.config import ExperimentConfig, ModelConfig, TrainConfig
from hfrep_tpu.train.trainer import GanTrainer


def main(epochs: int = 100) -> None:
    assert jax.default_backend() == "tpu", "soak wants the real chip"
    w, f, h = 672, 36, 100
    half = epochs // 2
    # Checkpoints land on steps_per_call=25 block boundaries, and the
    # resume leg restores ckpt_{half}: both halves must be whole blocks.
    assert epochs % 50 == 0 and epochs > 0, \
        f"epochs must be a positive multiple of 50 (2 x steps_per_call), got {epochs}"
    ckdir = tempfile.mkdtemp(prefix="sp_soak_")
    cfg = ExperimentConfig(
        model=ModelConfig(family="mtss_wgan_gp", hidden=h, window=w, features=f),
        train=TrainConfig(batch_size=32, n_critic=5, steps_per_call=25,
                          checkpoint_dir=ckdir, checkpoint_every=half,
                          log_every=25),
    )
    dataset = jax.random.uniform(jax.random.PRNGKey(5), (256, w, f), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("sp",))

    tr = GanTrainer(cfg, dataset, mesh=mesh)
    tr.train(epochs=epochs)
    assert tr.epoch == epochs and len(tr.history) == epochs
    assert all(np.isfinite(rec["d_loss"]) for rec in tr.history)
    rate = tr.steps_per_sec
    print(f"uninterrupted: {epochs} epochs, {rate:.1f} steps/s steady, "
          f"d_loss[-1]={tr.history[-1]['d_loss']:.4f}")

    tr2 = GanTrainer(cfg, dataset, mesh=mesh)
    tr2.restore_checkpoint(f"{ckdir}/ckpt_{half}")
    assert tr2.epoch == half
    tr2.train(epochs=epochs - half)
    err = max(
        float(jnp.abs(a - b).max()) for a, b in zip(
            jax.tree_util.tree_leaves((tr.state.g_params, tr.state.d_params)),
            jax.tree_util.tree_leaves((tr2.state.g_params, tr2.state.d_params))))
    assert err == 0.0, f"resumed run diverged: max|Δ|={err}"
    print(f"sp_trainer_soak ok: W={w} epochs={epochs} resume@{half} "
          f"bitwise-exact (max|Δ|=0.0) steps/s={rate:.1f} ckpts={ckdir}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 100)
