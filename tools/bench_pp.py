"""Measure the layer-pipeline (pp) axis against its competitors on the
same 2 devices (VERDICT r4 item 9 — the depth-axis negative).

Three steps, identical flagship semantics, identical (B=32, W, F, H):

  plain   single-device step (1 device busy)
  dp=2    batch split over ('dp', 2) — the incumbent use of 2 devices
  pp M=k  depth split over ('pp', 2), microbatches M ∈ {1, 2, 4}

Run on the 8-virtual-device CPU mesh (the only multi-device host we
have; the schedule and collectives are the real ones, the clock is a
CPU's).  The chip-anchored prediction — supersteps × per-timestep
latency with the measured ~2 µs floor from the sp microbatch study —
is printed next to each measurement; on TPU the recurrence is
latency-bound at these shapes, so the CPU ratios UNDERSTATE pp's
penalty wherever CPU matmul time scales with Bm (the chip's doesn't).

run: python tools/bench_pp.py [--window 48] [--reps 5]
(forces the CPU backend itself — sitecustomize's JAX_PLATFORMS=axon pin
is overridden via jax.config.update, the only override that wins)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax

# The image's sitecustomize pins JAX_PLATFORMS=axon (the tunneled TPU);
# config.update is the override that actually wins (tests/conftest.py).
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from hfrep_tpu.obs import timeline
from hfrep_tpu.config import ModelConfig, TrainConfig
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.train.states import init_gan_state


def _time_step(step, state, reps, label=None):
    from hfrep_tpu.obs import get_obs
    obs = get_obs()
    t0 = timeline.clock()
    state, m = step(state, jax.random.PRNGKey(99))      # compile + warm
    jax.block_until_ready(m["d_loss"])
    obs.record_span("block", timeline.clock() - t0, steps=1, warmup=True,
                    synced=True, config=label)
    t0 = timeline.clock()
    for r in range(reps):
        state, m = step(state, jax.random.PRNGKey(100 + r))
        jax.block_until_ready(m["d_loss"])
    dt = timeline.clock() - t0
    obs.record_span("block", dt, steps=reps, warmup=False, synced=True,
                    config=label)
    return dt / reps * 1e3                              # ms/epoch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--window", type=int, default=48)
    ap.add_argument("--features", type=int, default=35)
    ap.add_argument("--hidden", type=int, default=100)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--obs-dir", default=None,
                    help="emit the run through hfrep_tpu.obs (manifest + "
                         "events.jsonl) so bench trajectories diff with "
                         "`python -m hfrep_tpu.obs report A B`")
    args = ap.parse_args()

    import hfrep_tpu.obs as obs_pkg
    with obs_pkg.session(args.obs_dir, command="bench_pp") as obs:
        _bench(args, obs)


def _bench(args, obs):
    # dp leg runs the unified partition-rule mesh launch (ROADMAP item 1);
    # the pp legs keep the manual GPipe schedule (layer_pipeline.py — the
    # one semantics pjit cannot express).  The `mesh` section documents
    # the layout under config (NOT the top-level comparability-key slot).
    from hfrep_tpu.parallel.rules import MeshSpec
    obs.annotate(config={"model": {"family": "mtss_wgan_gp",
                                   "window": args.window,
                                   "features": args.features,
                                   "hidden": args.hidden},
                         "train": {"batch_size": 32},
                         "mesh": MeshSpec(dp=2).describe()})

    from hfrep_tpu.parallel import make_dp_multi_step
    from hfrep_tpu.parallel.layer_pipeline import make_pp_train_step
    from hfrep_tpu.train.steps import make_train_step

    mcfg = ModelConfig(family="mtss_wgan_gp", window=args.window,
                       features=args.features, hidden=args.hidden)
    tcfg = TrainConfig(batch_size=32, steps_per_call=1, lstm_backend="xla")
    dataset = jax.random.uniform(
        jax.random.PRNGKey(0), (256, args.window, args.features))
    pair = build_gan(mcfg)

    def fresh():
        return init_gan_state(jax.random.PRNGKey(1), mcfg, tcfg, pair)

    rows = []
    t_plain = _time_step(jax.jit(make_train_step(pair, tcfg, dataset)),
                         fresh(), args.reps, label="plain")
    rows.append({"config": "plain (1 dev)", "ms_per_epoch": t_plain,
                 "vs_plain": 1.0, "chip_model": 1.0})

    dp_mesh = Mesh(np.asarray(jax.devices()[:2]), ("dp",))
    t_dp = _time_step(make_dp_multi_step(pair, tcfg, dataset, dp_mesh),
                      fresh(), args.reps, label="dp2")
    rows.append({"config": "dp=2", "ms_per_epoch": t_dp,
                 "vs_plain": t_dp / t_plain,
                 "chip_model": None})   # dp splits rows: latency-parity on chip

    pp_mesh = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
    from hfrep_tpu.parallel._compat import ShardMapUnavailable
    for m in (1, 2, 4):
        try:
            t_pp = _time_step(
                make_pp_train_step(pair, tcfg, dataset, pp_mesh,
                                   microbatches=m),
                fresh(), args.reps, label=f"pp2_m{m}")
        except ShardMapUnavailable as e:
            # pp is the one remaining manual (shard_map) schedule; on a
            # runtime without the API the dp/plain legs still measure
            print(f"bench_pp: pp M={m} skipped ({e})", file=sys.stderr)
            continue
        rows.append({"config": f"pp=2 M={m}", "ms_per_epoch": t_pp,
                     "vs_plain": t_pp / t_plain,
                     # latency-bound chip prediction: (M+1)·W·t vs 2·W·t
                     "chip_model": (m + 1) / 2})

    for r in rows:
        cm = "" if r["chip_model"] is None else f"  chip-model {r['chip_model']:.2f}x"
        print(f"{r['config']:14s} {r['ms_per_epoch']:9.1f} ms/epoch  "
              f"{r['vs_plain']:.2f}x plain{cm}")
    from hfrep_tpu.utils.checkpoint import atomic_text
    atomic_text("results/bench_pp.json",
                json.dumps({"window": args.window, "rows": rows}, indent=2))
    for r in rows:
        obs.gauge(f"bench/{r['config']}/ms_per_epoch").set(
            r["ms_per_epoch"], vs_plain=r["vs_plain"])
    obs.memory_snapshot(phase="bench_end")


if __name__ == "__main__":
    main()
