"""Sweep wall-time measurement (RESULTS.md): trains 21 latents in one
vmapped program, then times the one-program vmapped evaluation vs the
round-1 host-serial engine loop on the real panel."""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import dataclasses, jax, jax.numpy as jnp, numpy as np
from hfrep_tpu.obs import timeline
from hfrep_tpu.config import AEConfig
from hfrep_tpu.core.data import load_panel
from hfrep_tpu.models.autoencoder import latent_mask
from hfrep_tpu.replication.engine import (ReplicationEngine, sweep_autoencoders,
                                          sweep_evaluate)
from hfrep_tpu.replication import perf_stats

panel = load_panel()
x_train, x_test, y_train, y_test = panel.train_test_split()
rf_test = panel.rf[x_train.shape[0]:]
dims = list(range(1, 22))
cfg = dataclasses.replace(AEConfig(), latent_dim=21)
eng = ReplicationEngine(x_train, y_train, x_test, y_test, cfg)

t0 = timeline.clock()
swept = sweep_autoencoders(jax.random.PRNGKey(0), eng.x_train, cfg, dims)
jax.block_until_ready(swept.params)
t_train = timeline.clock() - t0

masks = jnp.stack([latent_mask(d, 21) for d in dims])
t0 = timeline.clock()
ev = jax.device_get(sweep_evaluate(eng.model, cfg, eng.x_train, eng.x_test,
                                   eng.y_test, jnp.asarray(rf_test, jnp.float32),
                                   jnp.asarray(panel.factors, jnp.float32),
                                   swept.params, masks))
t_eval_vmap = timeline.clock() - t0

t0 = timeline.clock()
for i, d in enumerate(dims):
    params_i = jax.tree_util.tree_map(lambda a: a[i], swept.params)
    eng.use_params(params_i, latent_mask(d, 21))
    eng.model_IS_r2(); eng.model_IS_RMSE()
    eng.model_OOS_r2(); eng.model_OOS_RMSE()
    ante = eng.ante(rf_test); eng.post(panel.factors); eng.turnover()
    np.asarray(perf_stats.annualized_sharpe(jnp.asarray(ante),
               jnp.asarray(rf_test, jnp.float32)[-ante.shape[0]:]))
t_eval_serial = timeline.clock() - t0

print(f"train 21 latents (vmapped, 1000-epoch cap): {t_train:.2f}s")
print(f"eval 21 latents vmapped one-program:        {t_eval_vmap:.2f}s (incl. compile)")
print(f"eval 21 latents host-serial (round-1 path): {t_eval_serial:.2f}s")
print("best OOS r2 latent:", dims[int(np.argmax(np.asarray(ev['oos_r2']).mean(1)))],
      "mean:", float(np.asarray(ev['oos_r2']).mean(1).max()))
