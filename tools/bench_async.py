"""Async actor fabric probe: overlapped vs sequential pipeline wall clock.

The paper's pipeline runs GAN synthesis and the AE replication sweep as
two serialized phases; :mod:`hfrep_tpu.orchestrate` decouples them into
generator and consumer actor pools over a bounded queue, so the phases
overlap (arxiv 2111.04628's producer/consumer split under arxiv
2104.06272's supervision).  This probe measures what the overlap buys —
and what the fabric costs — on this host:

* **sequential** — generate every item, then sweep every item, one
  process, phases serialized (the pre-fabric drive; warmed program, so
  compile is excluded like every bench here);
* **overlapped** — the same items through :func:`~hfrep_tpu.orchestrate.
  run_pipeline` (2 generator actors + consumer actors over the spool
  queue).  The pipeline time INCLUDES member spawn and any cold child
  compile — the honest price of the fabric; the persistent compilation
  cache amortizes the compile across invocations.

Generator latency is modeled with a deterministic per-item delay
(``gen_delay`` — the fixture source's stand-in for real GAN sampling
cost, which on an accelerator runs concurrently with consumer training).
The overlap win scales with it: serial pays ``sum(gen) + sum(sweep)``,
the fabric pays ``~max(sum(gen)/P, sum(sweep)/C)`` + orchestration
overhead.  At self-test shapes the spawn overhead can dominate — the
SELF-CHECK therefore asserts *correctness* (the fabric's artifacts are
bit-identical to the sequential reference — the determinism contract)
and completion, and reports the speedup un-asserted.

Prints ONE JSON line.  Exit 0 = self-check passed, 1 = check or history
regression, 2 = tooling failure.  With ``HFREP_OBS_DIR`` the
measurements land as ``bench`` spans + ``bench/async_*`` gauges and gate
against the rolling history baseline exactly like ``bench_ae.py``.
"""

from __future__ import annotations

import argparse
import hashlib
import io
import json
import os
import sys
import tempfile
import time

if __name__ == "__main__":                     # `python tools/bench_async.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

from hfrep_tpu.obs import timeline
import hfrep_tpu.obs as obs_pkg
from hfrep_tpu.config import AEConfig
from hfrep_tpu.orchestrate import PipelinePlan, SourceSpec, run_pipeline
from hfrep_tpu.orchestrate.actors import _fixture_panel
from hfrep_tpu.replication.engine import sweep_item_arrays
from hfrep_tpu.utils import checkpoint as ckpt


def _plan(out_dir: str, self_test: bool) -> PipelinePlan:
    if self_test:
        rows, feats, latents = 32, 4, [1, 2]
        epochs, chunk, blocks, consumers, delay = 6, 3, 2, 1, 0.15
    else:
        rows, feats, latents = 120, 16, list(range(1, 9))
        epochs, chunk, blocks, consumers, delay = 120, 30, 4, 2, 0.5
    cfg = AEConfig(n_factors=feats, latent_dim=max(latents), epochs=epochs,
                   batch_size=16 if self_test else 48, patience=3, seed=0,
                   chunk_epochs=chunk)
    sources = [SourceSpec(name=f"b{i}", mode="fixture",
                          params={"rows": rows, "feats": feats,
                                  "gen_delay": delay})
               for i in range(2)]
    return PipelinePlan(out_dir=out_dir, sources=sources, blocks=blocks,
                        consumers=consumers, capacity=2, ae_cfg=cfg,
                        latent_dims=latents, consume_mode="direct",
                        stream_seed=3, timeout=600.0)


def _item_delay(plan: PipelinePlan) -> float:
    return float(plan.sources[0].params.get("gen_delay", 0.0))


def _sequential(plan: PipelinePlan):
    """Phase-serialized reference: all generation, then all sweeps.
    Returns (wall_secs, {source: {seq: aggregate_digest}}) — the digests
    in the exact format the fabric's artifact checksums use, so the two
    paths are byte-comparable."""
    import jax

    delay = _item_delay(plan)
    items = []
    # warm the sweep program so the sequential side excludes compile
    warm_key = jax.random.PRNGKey(plan.ae_cfg.seed)
    warm_panel = _fixture_panel(plan.stream_seed, 0, 0,
                                plan.sources[0].params["rows"],
                                plan.sources[0].params["feats"])
    sweep_item_arrays(warm_key, warm_panel, plan.ae_cfg, plan.latent_dims)

    t0 = timeline.clock()
    for idx, src in enumerate(plan.sources):      # phase 1: generation
        for seq in range(plan.blocks):
            if delay > 0.0:
                time.sleep(delay)
            items.append((idx, src.name, seq, _fixture_panel(
                plan.stream_seed, idx, seq, src.params["rows"],
                src.params["feats"])))
    digests: dict = {src.name: {} for src in plan.sources}
    for idx, name, seq, panel in items:           # phase 2: sweeps
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(plan.ae_cfg.seed), idx),
            seq)
        arrays = sweep_item_arrays(key, panel, plan.ae_cfg,
                                   plan.latent_dims)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        digests[name][f"{seq:05d}"] = ckpt.aggregate_digest(
            {"sweep.npz": hashlib.sha256(buf.getvalue()).hexdigest()})
    return timeline.clock() - t0, digests


def run_probe(obs, self_test: bool) -> int:
    with tempfile.TemporaryDirectory(prefix="hfrep_bench_async_") as td:
        plan = _plan(os.path.join(td, "pipe"), self_test)
        obs.annotate(config={
            "model": {"family": "async_pipeline",
                      "window": plan.sources[0].params["rows"],
                      "features": plan.sources[0].params["feats"],
                      "hidden": max(plan.latent_dims)},
            "train": {"batch_size": plan.ae_cfg.batch_size}})

        seq_s, seq_digests = _sequential(plan)

        t0 = timeline.clock()
        out = run_pipeline(plan)
        pipe_s = timeline.clock() - t0
        pipe_digests = {name: doc["items"]
                        for name, doc in out["summary"]["sources"].items()}

        n_items = len(plan.sources) * plan.blocks
        obs.record_span("bench", seq_s, steps=n_items, synced=True,
                        config="async_sequential")
        obs.record_span("bench", pipe_s, steps=n_items, synced=True,
                        config="async_overlapped")
        speedup = seq_s / pipe_s if pipe_s > 0 else float("inf")

        problems = []
        if pipe_digests != seq_digests:
            problems.append("fabric artifacts differ from the sequential "
                            "reference (determinism contract broken)")
        if out["stats"]["restarts"] != 0:
            problems.append(f"unexpected member restarts: "
                            f"{out['stats']['restarts']}")

        print(json.dumps({
            "metric": "async_overlap_speedup",
            "value": round(speedup, 3),
            "unit": "x",
            "sequential_s": round(seq_s, 4),
            "overlapped_s": round(pipe_s, 4),
            "items": n_items,
            "gen_delay_s": _item_delay(plan),
            "sources": len(plan.sources),
            "consumers": plan.consumers,
            "self_check": "ok" if not problems else "; ".join(problems),
            "self_test": bool(self_test),
        }))

        for name, value in (("async_overlap_speedup", speedup),
                            ("async_sequential_s", seq_s),
                            ("async_overlapped_s", pipe_s)):
            if np.isfinite(value):
                obs.gauge(f"bench/{name}").set(float(value))
        obs.memory_snapshot(phase="bench_async_end")

        if problems:
            print(f"bench_async: SELF-CHECK FAILED: {'; '.join(problems)}",
                  file=sys.stderr)
            return 1
        return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_async",
        description="async actor fabric overlap probe (orchestrate/)")
    ap.add_argument("--self-test", action="store_true",
                    help="tiny shapes: bit-identity + completion checks "
                         "in under a minute on CPU")
    args = ap.parse_args(argv)

    obs_dir = os.environ.get("HFREP_OBS_DIR")
    with obs_pkg.session_or_off(obs_dir, "bench_async",
                                command="bench_async") as obs:
        if obs_dir and not obs.enabled:
            obs_dir = None                 # degraded: nothing to gate below
        rc = run_probe(obs, args.self_test)
    from hfrep_tpu.obs import history as hist_mod
    hist = hist_mod.resolve_history(obs_dir)
    if obs_dir and hist:
        rc = hist_mod.gate_and_ingest(obs_dir, hist, rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
