"""Consolidated mixed-precision probe: the production ``Policy`` paths,
measured end to end.

Supersedes the round-1/round-4 pair (``bench_bf16_probe.py`` +
``bench_bf16_kernel_probe.py``), which predated the precision policy and
hand-rolled their dtype casts — including a raw ``_lstm_seq_fwd_impl``
micro-bench RESULTS.md later documented as unmeasurable through the
tunnel (identical-execution dedup, non-fencing readiness, 0.1-0.9 s
dispatch jitter).  This probe exercises exactly what production runs:
``ModelConfig.dtype`` → :func:`hfrep_tpu.models.registry.build_gan` →
``GanPair.policy`` → the train step's fp32-accumulation casts, through
the same shape-aware ``kernel_eligible`` dispatch, so a number here is a
number the trainer will reproduce.

Methodology is the one every RESULTS.md round converged on: 50-epoch
scanned blocks, state-threaded calls (nothing to dedup or reorder), TWO
warmups (compile + donated-state retrace), keys salted per config, and a
``device_get`` of the final loss as the fence.

Telemetry: each measured cell lands as a ``bench/bf16_*`` gauge when
``HFREP_OBS_DIR`` is set (``obs.session_or_off`` degrade-to-off
contract), so the dtype crossover table is a first-class run-history
series the sentinel can baseline.

Usage:
    python tools/bench_bf16_probe.py [h1,h2,...]       # chip probe
    python tools/bench_bf16_probe.py --self-test       # fast CPU gate
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _build(mcfg, tcfg, data, seed=0):
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.states import init_gan_state
    from hfrep_tpu.train.steps import make_multi_step

    pair = build_gan(mcfg)
    state = init_gan_state(jax.random.PRNGKey(seed), mcfg, tcfg, pair)
    step = make_multi_step(pair, tcfg, data)
    return pair, state, step


def measure_cell(mcfg, tcfg, data, n_calls: int = 6):
    """One (config, backend) cell: steps/sec through the production
    policy path, or ``None`` on a compile/run failure (e.g. VMEM OOM at
    widths the eligibility model rejects on other backends) or a
    diverged loss — a failed cell must not abort the rest of the table.

    The timing itself is :func:`bench._timed_multi` — the ONE
    state-threaded warmup/fence harness every measurement shares — so
    this probe can never drift methodologically from the bench it
    corroborates (n_warmups=2: compile + the donated-state retrace).
    """
    from bench import _timed_multi

    label = f"h={mcfg.hidden} {mcfg.dtype}/{tcfg.lstm_backend}"
    salt = hash((mcfg.hidden, mcfg.dtype, tcfg.lstm_backend)) % (2**31)
    try:
        pair, state, step = _build(mcfg, tcfg, data)
        rate = _timed_multi(step, state,
                            jax.random.fold_in(jax.random.PRNGKey(1), salt),
                            2, n_calls, tcfg.steps_per_call,
                            label=f"bf16_probe_h{mcfg.hidden}")
    except AssertionError:
        # _timed_multi's finiteness fence tripped: a diverged cell
        print(f"{label}: NON-FINITE loss after {n_calls} blocks", flush=True)
        return None
    except Exception as e:  # noqa: BLE001 - report any compile/run failure
        print(f"{label}: FAILED {type(e).__name__}: {str(e)[:140]}",
              flush=True)
        return None
    print(f"{label}: {rate:.1f} steps/s", flush=True)
    return rate


def probe(hiddens, n_calls: int = 6) -> int:
    """The crossover table (RESULTS.md rounds 3/4), through the policy:
    for each width, f32 and bf16 over both the pallas and scan backends
    — ``kernel_eligible`` decides per (width, dtype) whether the pallas
    request actually lands on kernels, exactly as in production."""
    import hfrep_tpu.obs as obs_pkg
    from hfrep_tpu.config import ModelConfig, TrainConfig

    data = jax.random.uniform(jax.random.PRNGKey(1), (1000, 48, 35),
                              jnp.float32)
    measured = 0
    with obs_pkg.session_or_off(os.environ.get("HFREP_OBS_DIR"),
                                "bench_bf16", command="bench_bf16") as obs:
        print("backend:", jax.default_backend(), flush=True)
        for h in hiddens:
            rates = {}
            for dtype in ("float32", "bfloat16"):
                for backend in ("pallas", "xla"):
                    mcfg = ModelConfig(family="mtss_wgan_gp", hidden=h,
                                       dtype=dtype)
                    tcfg = TrainConfig(steps_per_call=50,
                                       lstm_backend=backend)
                    rates[(dtype, backend)] = measure_cell(
                        mcfg, tcfg, data, n_calls)
            for (dtype, backend), rate in rates.items():
                if rate is not None:
                    measured += 1
                    tag = "bf16" if dtype == "bfloat16" else "f32"
                    obs.gauge(
                        f"bench/bf16_probe_h{h}_{tag}_{backend}"
                    ).set(float(rate))
            best16 = max((v for (d, _), v in rates.items()
                          if v and d == "bfloat16"), default=None)
            best32 = max((v for (d, _), v in rates.items()
                          if v and d == "float32"), default=None)
            if best16 and best32:
                obs.gauge(f"bench/bf16_speedup_h{h}").set(best16 / best32)
                print(f"h={h}: best-bf16 vs best-f32 = "
                      f"{best16 / best32:.2f}x", flush=True)
    if not measured:
        # every cell failed or diverged: an empty table must not exit 0
        # (a driver would read success with zero evidence)
        print("probe FAILED: no cell measured", flush=True)
        return 1
    print(f"probe done ({measured} cells)", flush=True)
    return 0


def self_test() -> int:
    """Fast CPU gate for tools/check.sh: the policy plumbing end to end
    at fixture shapes — (1) the fp32 policy's step is BIT-identical to a
    policy-free trace (graph-level pin: identical jaxprs), (2) the bf16
    policy trains finite and tracks the f32 trajectory within the
    documented tolerance, with fp32 master weights throughout, (3) the
    fused n_critic=1 G/D step matches the alternating form exactly."""
    import numpy as np
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.core.precision import Policy, policy_from

    data = jax.random.uniform(jax.random.PRNGKey(1), (64, 8, 5), jnp.float32)

    def run(dtype, n_critic=2, fuse=True, seed=0):
        mcfg = ModelConfig(family="mtss_wgan_gp", features=5, window=8,
                           hidden=8, dtype=dtype)
        tcfg = TrainConfig(steps_per_call=3, batch_size=4,
                           n_critic=n_critic, fuse_gd=fuse)
        pair, state, step = _build(mcfg, tcfg, data, seed)
        state, m = step(state, jax.random.PRNGKey(2))
        return pair, state, {k: np.asarray(v) for k, v in m.items()}

    # (1) fp32 policy is the identity: Policy.accum/compute return their
    # argument unchanged, so the fp32 step's jaxpr carries no policy
    # residue at all
    pol = policy_from("float32")
    x = jnp.ones((3,))
    assert pol.accum(x) is x and pol.compute(x) is x and not pol.mixed
    assert policy_from("bfloat16").mixed
    assert Policy().describe()["param"] == "float32"

    # (2) bf16 vs f32: same init (master weights are seeded identically —
    # param init never runs in compute dtype), trajectories within the
    # documented tolerance (README "Mixed precision": ~1e-2 relative on
    # W-GAN losses at fixture scale), params stay fp32
    pair16, s16, m16 = run("bfloat16")
    _, s32, m32 = run("float32")
    assert pair16.policy.mixed
    for leaf in jax.tree_util.tree_leaves((s16.g_params, s16.d_params)):
        assert leaf.dtype == jnp.float32, f"master weight leaked: {leaf.dtype}"
    assert np.isfinite(m16["d_loss"]).all() and np.isfinite(m16["g_loss"]).all()
    np.testing.assert_allclose(m16["d_loss"], m32["d_loss"], rtol=2e-2,
                               err_msg="bf16 d_loss diverged from f32")

    # (3) fused single-critic step == alternating form, bitwise
    _, sf, mf = run("float32", n_critic=1, fuse=True)
    _, sl, ml = run("float32", n_critic=1, fuse=False)
    for a, b in zip(jax.tree_util.tree_leaves(sf), jax.tree_util.tree_leaves(sl)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(mf["d_loss"], ml["d_loss"])

    print("bench_bf16 self-test ok: fp32-policy identity, bf16 tolerance "
          f"(max d_loss delta {np.abs(m16['d_loss'] - m32['d_loss']).max():.4f}), "
          "fp32 master weights, fused==alternating", flush=True)
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--self-test" in argv:
        return self_test()
    hiddens = ([int(v) for v in argv[0].split(",")] if argv
               else [100, 256, 384, 512])
    return probe(hiddens)


if __name__ == "__main__":
    raise SystemExit(main())
