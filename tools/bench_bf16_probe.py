"""Decision probe for the bf16 kernel question (VERDICT r1 item 3).

Times the pallas LSTM forward traversal with f32 vs bf16 operand
streams at the two real shapes, plus the end-to-end MTSS-WGAN-GP train
step in f32-pallas vs bf16-scan, on the real chip.  The outcome decides
whether the full bf16 backward/adjoint kernel path is worth building or
whether f32 is already optimal at these shapes (documented either way in
RESULTS.md).
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp

from hfrep_tpu.ops.pallas_lstm import LANE, _lstm_seq_fwd_impl, pad_keras_params


def time_fn(fn, *args, iters=50):
    out = jax.block_until_ready(fn(*args))          # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def _probe_hidden_sizes(hiddens=(100, 256, 384, 512), n_calls=6):
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.states import init_gan_state
    from hfrep_tpu.train.steps import make_multi_step

    data = jax.random.uniform(jax.random.PRNGKey(1), (1000, 48, 35), jnp.float32)
    for h in hiddens:
        rates = {}
        for label, dtype, backend in [("f32/pallas", "float32", "pallas"),
                                      ("bf16/pallas", "bfloat16", "pallas"),
                                      ("bf16/scan", "bfloat16", "xla"),
                                      ("f32/scan", "float32", "xla")]:
            mcfg = ModelConfig(family="mtss_wgan_gp", hidden=h, dtype=dtype)
            tcfg = TrainConfig(steps_per_call=50, lstm_backend=backend)
            pair = build_gan(mcfg)
            state = init_gan_state(jax.random.PRNGKey(0), mcfg, tcfg, pair)
            step = make_multi_step(pair, tcfg, data)
            try:
                state, m = step(state, jax.random.PRNGKey(1))
                jax.block_until_ready(m)
            except Exception as e:                    # e.g. VMEM OOM at large H
                rates[label] = None
                print(f"  hidden={h} {label}: FAILED "
                      f"({type(e).__name__}: {str(e)[:120]}...)")
                continue
            t0 = time.perf_counter()
            for i in range(n_calls):
                state, m = step(state, jax.random.fold_in(jax.random.PRNGKey(2), i))
            jax.block_until_ready(m)
            rates[label] = n_calls * 50 / (time.perf_counter() - t0)
            assert jnp.isfinite(m["d_loss"]).all()
        ok = {k: v for k, v in rates.items() if v}
        best16 = max((v for k, v in ok.items() if k.startswith("bf16")),
                     default=None)
        best32 = max((v for k, v in ok.items() if k.startswith("f32")), default=None)
        ratio = (f"  -> best-bf16 vs best-f32: {best16/best32:.2f}x"
                 if best16 and best32 else "")
        print(f"hidden={h}: " + "  ".join(
            f"{k} {v:.1f}/s" if v else f"{k} n/a" for k, v in rates.items()) + ratio)


def main():
    print("backend:", jax.default_backend())
    fwd = jax.jit(lambda xz, rec: _lstm_seq_fwd_impl(xz, rec, "sigmoid",
                                                     with_cs=False))
    for (b, w, h) in [(32, 48, 100), (32, 168, 100)]:
        hp = ((h + LANE - 1) // LANE) * LANE
        k_xz, k_rec = jax.random.split(jax.random.PRNGKey(0))
        xz32 = jax.random.normal(k_xz, (w, b, 4 * hp), jnp.float32)
        rec32 = jax.random.normal(k_rec, (hp, 4 * hp), jnp.float32) * 0.05
        t32, h32 = time_fn(fwd, xz32, rec32)
        t16, h16 = time_fn(fwd, xz32.astype(jnp.bfloat16), rec32.astype(jnp.bfloat16))
        err = float(jnp.abs(h32 - h16).max())
        print(f"fwd traversal (B={b}, W={w}, Hp={hp}): "
              f"f32 {t32*1e6:.1f}us  bf16-operands {t16*1e6:.1f}us "
              f"({t32/t16:.2f}x)  max|Δh|={err:.2e}")

    # Larger-model probe (VERDICT r2 item 7): the forward kernel accepts
    # bf16 operand streams "for larger-model reuse" — measure where (if
    # anywhere) that actually pays.  Isolated traversal timings through
    # the tunnel proved unmeasurable in BOTH directions (identical-
    # execution dedup, non-fencing readiness, 0.1-0.9 s latency jitter —
    # even a reps=300 vs reps=3000 slope method returns negative slopes),
    # so the instrument is the same state-threaded end-to-end loop
    # bench.py uses: each dispatch consumes the previous dispatch's
    # state, which nothing can dedup or reorder, and 50 epochs/dispatch
    # dwarf the jitter.  Scaling `hidden` scales the recurrent matmul
    # (the op whose operand width bf16 halves) quadratically.
    print("--- larger-model probe: end-to-end train epochs at hidden=H ---")
    _probe_hidden_sizes()

    # End-to-end: one flagship train epoch, f32+pallas vs bf16+scan.
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.states import init_gan_state
    from hfrep_tpu.train.steps import make_multi_step

    data = jax.random.uniform(jax.random.PRNGKey(1), (1000, 48, 35), jnp.float32)
    for label, dtype, backend in [("f32/pallas", "float32", "pallas"),
                                  ("bf16/scan", "bfloat16", "xla"),
                                  ("f32/scan", "float32", "xla")]:
        mcfg = ModelConfig(family="mtss_wgan_gp", dtype=dtype)
        tcfg = TrainConfig(steps_per_call=50, lstm_backend=backend)
        pair = build_gan(mcfg)
        state = init_gan_state(jax.random.PRNGKey(0), mcfg, tcfg, pair)
        step = make_multi_step(pair, tcfg, data)
        state, m = step(state, jax.random.PRNGKey(1))
        jax.block_until_ready(m)
        t0 = time.perf_counter()
        for i in range(4):
            state, m = step(state, jax.random.fold_in(jax.random.PRNGKey(2), i))
        jax.block_until_ready(m)
        dt = time.perf_counter() - t0
        print(f"train epoch {label}: {200/dt:.1f} steps/s "
              f"(d_loss {float(m['d_loss'][-1]):.3f})")


if __name__ == "__main__":
    main()
