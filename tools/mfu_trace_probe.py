"""Shim: the MFU profiler cross-check folded into the consolidated
perf probe (ISSUE 13) — one profiling instrument on the
``hfrep_tpu.obs.attrib`` trace/fingerprint layer instead of private
parsing.  Kept so RESULTS.md's historical command lines keep working;
use ``tools/perf_probe.py mfu`` directly.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perf_probe import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main(["mfu"] + sys.argv[1:]))
