"""Profiler cross-check of the analytic MFU numbers (VERDICT r4 item 6).

`tools/flops_accounting.py` derives 19.6-21 TFLOP/s achieved from
analytic model FLOPs x measured steps/s (XLA's cost model can't see into
`pallas_call`, so analytic is the only option for the *numerator*).
This probe cross-checks the *time* side with the XLA profiler:

1. run a steady flagship block under `jax.profiler.trace`,
2. parse the emitted perfetto trace (`plugins/profile/*/*.trace.json.gz`),
3. sum per-op durations on the TPU device tracks -> device-busy time per
   epoch and the share spent inside the pallas LSTM kernels,
4. reconcile: analytic executed-FLOPs / trace device time = device-level
   TFLOP/s, to compare against the wall-clock-derived figure (they agree
   when the step is device-bound, i.e. wall ~= device-busy).

Falls back loudly if the tunneled axon platform emits no device events.

run (chip): python tools/mfu_trace_probe.py [--epochs 200]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from hfrep_tpu.config import ModelConfig, TrainConfig
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.train.states import init_gan_state
from hfrep_tpu.train.steps import make_train_step


def run_traced_block(log_dir: str, epochs: int) -> float:
    """Returns steady wall seconds for `epochs` epochs (compile excluded)."""
    mcfg = ModelConfig(family="mtss_wgan_gp")  # flagship (48, 35)
    tcfg = TrainConfig(batch_size=32, steps_per_call=epochs)
    key = jax.random.PRNGKey(0)
    dataset = jax.random.uniform(key, (512, mcfg.window, mcfg.features))
    pair = build_gan(mcfg)
    state = init_gan_state(jax.random.PRNGKey(1), mcfg, tcfg, pair)
    step = jax.jit(make_train_step(pair, tcfg, dataset), donate_argnums=0)
    state, m = step(state, jax.random.PRNGKey(2))     # compile + warm
    jax.block_until_ready(m["d_loss"])
    t0 = time.perf_counter()
    with jax.profiler.trace(log_dir):
        state, m = step(state, jax.random.PRNGKey(3))
        jax.block_until_ready(m["d_loss"])
    return time.perf_counter() - t0


def parse_trace(log_dir: str) -> dict:
    paths = glob.glob(os.path.join(log_dir, "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        return {"error": f"no trace file under {log_dir}"}
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        tr = json.load(f)
    ev = tr.get("traceEvents", [])
    # device tracks: process_name metadata containing "TPU" (e.g. "/device:TPU:0")
    pid_name, tid_name = {}, {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
        elif e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_name[(e["pid"], e["tid"])] = e["args"].get("name", "")
    dev_pids = {p for p, n in pid_name.items() if "TPU" in n.upper() or "device" in n.lower()}
    # Sum ONLY the leaf-op thread ("XLA Ops"): each device pid also carries
    # wrapper tracks ("XLA Modules", "Steps") whose events SPAN the leaf
    # ops — summing every X event on the pid would double/triple-count.
    op_tids = {pt for pt, n in tid_name.items()
               if pt[0] in dev_pids and "XLA Ops" in n}
    leaf_only = bool(op_tids)
    by_op = defaultdict(float)
    total = 0.0
    for e in ev:
        if e.get("ph") != "X":
            continue
        if leaf_only:
            if (e.get("pid"), e.get("tid")) not in op_tids:
                continue
        elif e.get("pid") not in dev_pids:
            continue
        dur = float(e.get("dur", 0.0)) * 1e-6        # us -> s
        by_op[e.get("name", "")] += dur
        total += dur
    top = sorted(by_op.items(), key=lambda kv: -kv[1])[:15]
    pallas = sum(d for n, d in by_op.items()
                 if "pallas" in n.lower() or "custom-call" in n.lower())
    return {"trace_file": os.path.relpath(path),
            "device_total_s": total,
            "leaf_op_thread_found": leaf_only,   # False ⇒ total may overcount
            "pallas_or_customcall_s": pallas,
            "top_ops": [(n, round(d, 4)) for n, d in top],
            "thread_names": sorted(set(tid_name.values()))[:20],
            "process_names": sorted(pid_name.values())}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=200)
    ap.add_argument("--log-dir", default="/tmp/mfu_trace")
    args = ap.parse_args()

    wall = run_traced_block(args.log_dir, args.epochs)
    info = parse_trace(args.log_dir)
    info["epochs"] = args.epochs
    info["wall_s"] = wall
    info["wall_steps_per_s"] = args.epochs / wall
    if "device_total_s" in info:
        # analytic executed FLOPs per epoch from flops_accounting (padded)
        from flops_accounting import epoch_flops, HP
        ex = epoch_flops(48, 35, HP)
        lo = epoch_flops(48, 35, 100)
        info["analytic_executed_gflops_per_epoch"] = ex / 1e9
        per_epoch_dev = info["device_total_s"] / args.epochs
        info["device_s_per_epoch"] = per_epoch_dev
        if per_epoch_dev > 0:
            info["device_tflops_executed"] = ex / per_epoch_dev / 1e12
            info["device_tflops_model"] = lo / per_epoch_dev / 1e12
            info["device_busy_frac_of_wall"] = info["device_total_s"] / wall
    print(json.dumps(info, indent=2))


if __name__ == "__main__":
    main()
