"""Profiler cross-check of the analytic MFU numbers (VERDICT r4 item 6).

`tools/flops_accounting.py` derives ~20 TFLOP/s achieved from analytic
model FLOPs x measured steps/s (XLA's cost model can't see into
`pallas_call`, so the *numerator* must be analytic).  This probe
cross-checks the *time* side with the XLA profiler, carefully, because
the tunneled axon runtime is involved:

1. **Calibration**: a jitted chain of K large matmuls with known FLOPs
   is wall-timed (device_get fence, distinct inputs) and then traced;
   trace-derived device time vs wall tells whether the trace's absolute
   scale can be trusted through the tunnel at all.
2. **Epoch trace**: ONE flagship train epoch under `jax.profiler.trace`;
   the perfetto trace's TPU "XLA Ops" track is reduced to
   *interval-union* busy time (events on the op track nest — a `while`
   op SPANS its body's ops, so a plain sum double-counts; the union
   doesn't), plus the summed span of the pallas LSTM custom-calls.
3. **Reconcile**: steady epoch wall (from an untraced 50-epoch block) vs
   trace busy time per epoch; analytic executed FLOPs / busy time =
   device-level TFLOP/s to compare with the wall-clock-derived figure.

run (chip): python tools/mfu_trace_probe.py
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hfrep_tpu.config import ModelConfig, TrainConfig
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.train.states import init_gan_state
from hfrep_tpu.train.steps import make_multi_step, make_train_step

# resolved via the repo-root sys.path entry above; imported at module top
# so a broken shim fails BEFORE the expensive traced run, not after (the
# old late `from flops_accounting import ...` also only resolved when
# launched as `python tools/...`)
from tools.flops_accounting import HP, epoch_flops


def _latest_trace(log_dir: str):
    paths = glob.glob(os.path.join(log_dir, "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        raise SystemExit(f"no perfetto trace emitted under {log_dir} — "
                         "this platform's profiler exported nothing; the "
                         "cross-check cannot run here")
    return max(paths, key=os.path.getmtime)


def _read_ops_events(path):
    """All complete events on TPU-pid 'XLA Ops' threads: [(name, ts, dur)]."""
    with gzip.open(path, "rt") as f:
        tr = json.load(f)
    ev = tr.get("traceEvents", [])
    pid_name, tid_name = {}, {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
        elif e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_name[(e["pid"], e["tid"])] = e["args"].get("name", "")
    dev_pids = {p for p, n in pid_name.items()
                if "TPU" in n.upper() or "device" in n.lower()}
    op_tids = {pt for pt, n in tid_name.items()
               if pt[0] in dev_pids and "XLA Ops" in n}
    out = []
    for e in ev:
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in op_tids:
            out.append((e.get("name", ""), float(e["ts"]), float(e.get("dur", 0.0))))
    return out, sorted(set(tid_name.values()))


def _interval_union_s(events) -> float:
    """Union length of [ts, ts+dur) — busy time without double-counting
    parents (`while`/fusion wrappers) that span their children."""
    ivs = sorted((ts, ts + d) for _, ts, d in events if d > 0)
    total, cur_a, cur_b = 0.0, None, None
    for a, b in ivs:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total * 1e-6                                   # us -> s


def calibrate(log_dir: str, k: int = 50, n: int = 2048) -> dict:
    """Known-FLOPs matmul chain: wall vs trace-derived device time."""
    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)

    @jax.jit
    def chain(a, b):
        def body(c, _):
            return (c @ b) / jnp.float32(n), None
        out, _ = jax.lax.scan(body, a, None, length=k)
        return out

    jax.device_get(chain(a, b))                           # compile + warm
    t0 = time.perf_counter()
    jax.device_get(chain(a * 1.0001, b))
    wall = time.perf_counter() - t0
    with jax.profiler.trace(log_dir):
        jax.device_get(chain(a * 1.0002, b))
    events, threads = _read_ops_events(_latest_trace(log_dir))
    busy = _interval_union_s(events)
    flops = 2.0 * k * n ** 3
    return {"matmul_wall_s": wall, "matmul_trace_busy_s": busy,
            "trace_vs_wall": busy / wall if wall else None,
            "wall_tflops": flops / wall / 1e12,
            "trace_tflops": (flops / busy / 1e12) if busy else None,
            "thread_names": threads}


def epoch_trace(log_dir: str) -> dict:
    mcfg = ModelConfig(family="mtss_wgan_gp")             # flagship (48, 35)
    key = jax.random.PRNGKey(0)
    dataset = jax.random.uniform(key, (512, mcfg.window, mcfg.features))
    pair = build_gan(mcfg)

    # steady wall per epoch: one untraced 50-epoch block, bench discipline
    tcfg50 = TrainConfig(batch_size=32, steps_per_call=50)
    state = init_gan_state(jax.random.PRNGKey(1), mcfg, tcfg50, pair)
    multi = make_multi_step(pair, tcfg50, dataset)
    state, m = multi(state, jax.random.PRNGKey(2))        # compile + warm
    float(jax.device_get(m["d_loss"]).reshape(-1)[-1])
    t0 = time.perf_counter()
    state, m = multi(state, jax.random.PRNGKey(3))
    float(jax.device_get(m["d_loss"]).reshape(-1)[-1])
    steady_epoch_wall = (time.perf_counter() - t0) / 50

    # ONE epoch traced
    tcfg1 = TrainConfig(batch_size=32, steps_per_call=1)
    st1 = init_gan_state(jax.random.PRNGKey(4), mcfg, tcfg1, pair)
    step = jax.jit(make_train_step(pair, tcfg1, dataset))
    st1, m1 = step(st1, jax.random.PRNGKey(5))            # compile + warm
    float(jax.device_get(m1["d_loss"]))
    with jax.profiler.trace(log_dir):
        st1, m1 = step(st1, jax.random.PRNGKey(6))
        float(jax.device_get(m1["d_loss"]))
    events, _ = _read_ops_events(_latest_trace(log_dir))
    busy = _interval_union_s(events)
    by_op = defaultdict(float)
    for n_, _, d in events:
        by_op[n_] += d * 1e-6
    # pallas kernels surface as custom-calls named after the traced fn
    # (LSTM/stack jvp/transpose chains) — match on the module names, and
    # union the intervals (matched events can nest, same trap as the
    # total).
    kern = _interval_union_s(
        [e for e in events if "LSTM" in e[0] or "lstm" in e[0]])
    top = sorted(by_op.items(), key=lambda kv: -kv[1])[:12]
    return {"steady_epoch_wall_s": steady_epoch_wall,
            "trace_busy_s": busy,
            "busy_frac_of_steady_wall": busy / steady_epoch_wall,
            "lstm_op_span_s": kern,
            "lstm_share_of_busy": kern / busy if busy else None,
            "top_ops_ms": [(n_, round(d * 1e3, 3)) for n_, d in top]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log-dir", default="/tmp/mfu_trace")
    args = ap.parse_args()

    out = {"calibration": calibrate(os.path.join(args.log_dir, "cal"))}
    ep = epoch_trace(os.path.join(args.log_dir, "epoch"))
    ex, lo = epoch_flops(48, 35, HP), epoch_flops(48, 35, 100)
    ep["analytic_executed_gflops"] = ex / 1e9
    ep["analytic_model_gflops"] = lo / 1e9
    if ep["trace_busy_s"]:
        ep["device_tflops_executed"] = ex / ep["trace_busy_s"] / 1e12
        ep["device_tflops_model"] = lo / ep["trace_busy_s"] / 1e12
    ep["wall_tflops_model"] = lo / ep["steady_epoch_wall_s"] / 1e12
    out["epoch"] = ep
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
