"""Reference-execution-model baseline: MTSS-WGAN-GP epochs/sec in TF/Keras.

Measures the semantic equivalent of the reference's hot loop
(``GAN/MTSS_WGAN_GP.py:254-287``): 5 RMSprop(5e-5) critic updates on the
3-term WGAN-GP loss (λ=10, per-sample α) + 1 generator update, batch 32,
(48, 35) windows, LSTM100×2 generator and LSTM100×2+Flatten critic — as
one tf.function per critic/generator step (already a *faster* execution
model than the reference's per-call ``train_on_batch`` graph launches).

Two anchors, selected with ``--threads``:

* ``--threads 1`` — the reference's own declared config: single-threaded
  session for reproducibility (``helper.py:38``,
  ``ConfigProto(intra_op_parallelism_threads=1, inter_op=1)``).
* ``--threads 0`` — TF defaults (unpinned): what a competently-run TF
  baseline would use.  NOTE: this host exposes a single CPU core
  (``nproc`` = 1), so unpinned ≈ pinned here; on a many-core host the
  unpinned anchor would be several× higher.

Threading must be configured before TF initializes, hence one process per
anchor.  Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
from hfrep_tpu.obs import timeline


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=1,
                    help="intra/inter op threads; 0 = TF defaults (unpinned)")
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()

    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
    import numpy as np
    import tensorflow as tf

    if args.threads > 0:
        tf.config.threading.set_intra_op_parallelism_threads(args.threads)
        tf.config.threading.set_inter_op_parallelism_threads(args.threads)

    tf.random.set_seed(123)
    np.random.seed(123)

    window, features, hidden, batch, n_critic, gp_w = 48, 35, 100, 32, 5, 10.0

    def build_generator():
        return tf.keras.Sequential([
            tf.keras.layers.Input((window, features)),
            tf.keras.layers.LSTM(hidden, activation="sigmoid", return_sequences=True),
            tf.keras.layers.LayerNormalization(),
            tf.keras.layers.LSTM(hidden, activation="sigmoid", return_sequences=True),
            tf.keras.layers.LeakyReLU(),
            tf.keras.layers.LayerNormalization(),
            tf.keras.layers.Dense(features),
        ])

    def build_critic():
        return tf.keras.Sequential([
            tf.keras.layers.Input((window, features)),
            tf.keras.layers.LSTM(hidden, return_sequences=True),
            tf.keras.layers.LSTM(hidden, return_sequences=True),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(1),
        ])

    gen, critic = build_generator(), build_critic()
    g_opt = tf.keras.optimizers.RMSprop(5e-5)
    d_opt = tf.keras.optimizers.RMSprop(5e-5)
    dataset = tf.constant(np.random.uniform(0, 1, (1000, window, features)),
                          tf.float32)

    @tf.function
    def critic_step(real, noise, alpha):
        fake = gen(noise, training=True)
        with tf.GradientTape() as tape:
            interp = alpha * real + (1.0 - alpha) * fake
            with tf.GradientTape() as gp_tape:
                gp_tape.watch(interp)
                s_interp = critic(interp, training=True)
            g = gp_tape.gradient(s_interp, interp)
            norms = tf.sqrt(tf.reduce_sum(g ** 2, axis=[1, 2]) + 1e-12)
            gp = tf.reduce_mean((1.0 - norms) ** 2)
            loss = (-tf.reduce_mean(critic(real, training=True))
                    + tf.reduce_mean(critic(fake, training=True)) + gp_w * gp)
        grads = tape.gradient(loss, critic.trainable_variables)
        d_opt.apply_gradients(zip(grads, critic.trainable_variables))
        return loss

    @tf.function
    def gen_step(noise):
        with tf.GradientTape() as tape:
            loss = -tf.reduce_mean(critic(gen(noise, training=True), training=True))
        grads = tape.gradient(loss, gen.trainable_variables)
        g_opt.apply_gradients(zip(grads, gen.trainable_variables))
        return loss

    def epoch():
        for _ in range(n_critic):
            idx = np.random.randint(0, 1000, batch)
            real = tf.gather(dataset, idx)
            noise = tf.constant(np.random.normal(0, 1, (batch, window, features)),
                                tf.float32)
            alpha = tf.constant(np.random.uniform(size=(batch, 1, 1)), tf.float32)
            critic_step(real, noise, alpha)
        gen_step(tf.constant(np.random.normal(0, 1, (batch, window, features)),
                             tf.float32))

    epoch()                                  # trace + warmup
    t0 = timeline.clock()
    for _ in range(args.epochs):
        epoch()
    dt = timeline.clock() - t0

    print(json.dumps({
        "metric": "tf_baseline_epochs_per_sec",
        "threads": args.threads or "default",
        "value": round(args.epochs / dt, 4),
        "epochs": args.epochs,
    }))


if __name__ == "__main__":
    main()
