#!/usr/bin/env bash
# Static-analysis gate: JAX-aware lint + shape contracts over the whole
# tree, plus the obs event-schema self-test.  Exit 0 = clean (fixed,
# # noqa'd, or baselined in hfrep_tpu/analysis/baseline.json) AND the
# committed telemetry fixture still parses; non-zero otherwise; 2 = usage.
#
#   tools/check.sh              # human output
#   tools/check.sh --format json
#
# Also runs inside tier-1 via tests/test_analysis_self.py, so CI fails
# on new violations even when this script isn't invoked directly.
set -euo pipefail
cd "$(dirname "$0")/.."
# Gates that tier-1 ALSO runs as standalone tests (test_resilience.py::
# test_resilience_selftest_smoke, test_ae_chunked.py::
# test_bench_ae_self_test_smoke, and the ISSUE-19 async-boundary pins in
# test_ae_chunked.py/test_async_boundary.py) can be skipped BY NAME via
# HFREP_CHECK_SKIP_GATES when the caller is itself inside tier-1
# (tests/test_analysis_self.py) — the suite has a hard global wall clock
# and running the same gate twice per CI tier buys nothing.  Standalone
# check.sh invocations keep the full battery: the knob is opt-in, like
# HFREP_CHAOS_MIN/HFREP_CHAOS_BUDGET below.
skip_gate() {
    case ",${HFREP_CHECK_SKIP_GATES:-}," in
        *",$1,"*) echo "check.sh: gate '$1' skipped (HFREP_CHECK_SKIP_GATES)" 1>&2
                  return 0;;
    esac
    return 1
}
# env-stripped like the self-tests below: the two-phase analyzer (and
# its HF002 spec checks) must judge the tree, not whatever ambient
# fault plan / telemetry env this shell happens to carry.
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS \
    python -m hfrep_tpu.analysis check \
    hfrep_tpu tools tests bench.py bench_extra.py "$@"
# program audit (phase 3): abstractly trace every registered compile
# boundary (GAN step families, conditional, mesh, AE chunk/init, serve
# AOT heads) and run the JPX jaxpr/HLO rules — donation completeness,
# precision-policy conformance, host syncs in loop bodies, recompile
# hazards, sharding loss, scan-carry bloat.  Warm-cache runs never
# import jax (per-boundary results keyed on the defining modules' shas
# + the installed jax version).  CPU-pinned + env-stripped; status to
# stderr so `--format json` callers keep stdout pure.
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS JAX_PLATFORMS=cpu \
    python -m hfrep_tpu.analysis audit 1>&2
# drive-registry completeness gate (ISSUE 20): every registered
# DriveSpec's fixture resolves, its fault sites are registry-known, all
# six production drive families are covered, and the chaos subject list
# mirrors DRIVE_REGISTRY in both directions — a new long-running
# workload without chaos coverage fails HERE, not in review.
# Env-stripped like the analyzer above (the registry must be judged
# bare, not under an ambient fault plan).
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS \
    python -m hfrep_tpu.resilience drives --check 1>&2
# telemetry schema gate: writer (hfrep_tpu.obs) and parser (obs.report)
# must agree on the committed fixture run directory.  Status goes to
# stderr so `--format json` keeps stdout pure JSON for machine consumers.
python -m hfrep_tpu.obs report --self-test 1>&2
# perf-regression sentinel gate: ingest + cross-host merge + median/MAD
# baseline math + pass/fail verdicts over the committed history fixture
# (strict; emits one pure-JSON result doc, routed to stderr here for the
# same stdout-purity reason).
python -m hfrep_tpu.obs gate --self-test 1>&2
# perf-microscope diagnosis gate: the committed two-run explain fixture
# (base + planted regression) must yield a ranked diagnosis naming the
# planted causes — new HLO digests at compile:multi_step, the
# backend_compiles storm, the dispatch_frac jump — with base-vs-base
# staying silent.  Env-stripped like the other self-tests: an ambient
# HFREP_OBS_DIR/HFREP_HISTORY must not leak telemetry into (or a store
# under) a CI self-test.
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS \
    python -m hfrep_tpu.obs explain --self-test 1>&2
# fleet-telemetry gate: rollup ingestion + cross-replica invariants +
# SLO burn-rate math over the committed two-replica fleet fixture — the
# planted ledger drop (submitted 74 vs terminal 72, replica_b) and the
# shed burn breach must be caught, the healthy objectives must stay
# green, and the read-only evaluation must leave the fixture pristine.
# Env-stripped like the other self-tests; pure-JSON stdout → stderr.
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS \
    python -m hfrep_tpu.obs slo --self-test 1>&2
# wall-clock ledger gate: accumulator algebra + conservation invariant
# (Σ cat_ms == wall_ms on every emitted window), hand-computed fixture
# ledger, perfetto reconstruction byte-identical on a rotated+compacted
# dir, and torn-tail degradation (SIGKILLed run → larger unattributed,
# never a crash).  Env-stripped like the other self-tests.
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS \
    python -m hfrep_tpu.obs timeline --self-test 1>&2
# AE chunked-drive probe fast path: trains the early-exit fixture at tiny
# shapes and asserts the >=2x chunked-vs-monolithic win, so the probe (and
# the hot path it guards) can't rot.  Pinned to CPU (a self-test of the
# mechanism, not a measurement of the backend) and stripped of the
# telemetry env: ambient HFREP_OBS_DIR/HFREP_HISTORY must not make a CI
# self-test ingest a non-measurement record into the committed store.
skip_gate bench_ae || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python tools/bench_ae.py --self-test 1>&2
# async boundary engine gate (ISSUE 19): DB-vs-serial bit-identity on
# the early-stop fixture, one-chunk-overshoot accounting, and the
# overlap_frac floor for the deferred drive — including the synthetic
# leg that injects deterministic host-side sleeps into every chunk
# dispatch (a re-serialized boundary fails the floor).  Runs in ~10s
# at tiny shapes; throwaway obs sessions, never ingested.  Env-stripped
# + CPU-pinned like the other self-tests.
skip_gate bench_overlap || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python tools/bench_overlap.py --self-test 1>&2
# resilience gate: kill→resume bit-identical (REAL SIGTERM through the
# graceful-drain handler, 21-lane + multi-dataset AE sweeps at fixture
# shapes), corrupt/torn-checkpoint → fallback-to-previous-good, the
# async-fabric ensemble scenarios (hfrep_tpu/orchestrate): REAL SIGKILL
# of one generator actor of a running pipeline → supervisor restart from
# its sub-block snapshot → artifacts bit-identical; pod-wide drain
# barrier → pipeline resume bit-identical; and the serving chaos
# scenario (hfrep_tpu/serve): worker kill + result-publish EIO +
# deadline storm + overload burst with every request reaching exactly
# one typed terminal outcome, breaker → degraded-stale → close, REAL
# SIGTERM drain.  Each scenario runs under its own SIGALRM watchdog so
# one wedge fails loudly instead of eating this script's budget.
# CPU-pinned and env-stripped like the bench self-test: ambient
# HFREP_OBS_DIR/HFREP_HISTORY must not pollute the committed history
# store, and an ambient HFREP_FAULTS plan must not fire inside the gate.
skip_gate resilience || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python -m hfrep_tpu.resilience selftest 1>&2
# mixed-precision gate: the production Policy path end to end at fixture
# shapes — fp32-policy identity (bit-identical graphs), bf16-vs-f32
# trajectory tolerance with fp32 master weights, fused==alternating G/D
# at n_critic=1.  CPU-pinned + env-stripped like the other self-tests.
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python tools/bench_bf16_probe.py --self-test 1>&2
# serving gate: the overload envelope at tiny shapes — AOT-warmed
# programs, micro-batch load levels with zero silent drops and bounded
# p95, plus the chaos smoke (5ms deadline storm → typed misses; burst
# past the admission bound → typed sheds; injected result-publish EIO
# streak → breaker opens, serves flagged-stale degraded answers, closes
# after cooldown).  Env-stripped so ambient fault plans / history stores
# stay out of the gate.
skip_gate bench_serve || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python tools/bench_serve.py --self-test 1>&2
# crash-forensics drill (flight recorder): a real obs session drives a
# real (tiny) AE training on NaN-poisoned data with the health tripwire
# armed and io_fail@obs_append faults injected into the event stream;
# the NumericFault must land a COMPLETE checksum-verifying crash bundle
# (events tail + manifest + traceback + env) plus the forensic carry
# dump, and `report --crash` must render it.  Env-stripped + CPU-pinned
# like the other gates; runs in seconds.
skip_gate crash_drill || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS -u HFREP_HEALTH \
    JAX_PLATFORMS=cpu python -m hfrep_tpu.obs crash-drill 1>&2
# scenario-factory gate: bank determinism replay (same seed+regime ⇒
# identical aggregate digest, re-derived three independent ways), the
# 100-lane walk-forward preempt→resume bit-identity drill (injected
# preempt at a training chunk boundary AND a scoring window boundary;
# resumed surface byte-identical to an undisturbed run), universe
# synthesis determinism.  Env-stripped + CPU-pinned like the others.
skip_gate bench_scenario || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python tools/bench_scenario.py --self-test 1>&2
# chaos-search gate (ISSUE 14): replay the committed regression corpus
# (hfrep_tpu/resilience/_chaos_corpus/ — every entry a shrunk schedule
# that once violated an invariant, green forever at HEAD), then a
# seeded budgeted soak of random fault schedules over the real
# subjects (chunked AE sweep, padded multi-sweep, GAN ckpt/resume,
# serving load, walk-forward), judged by the shared oracles
# (exit-code contract, resume bit-identity, atomic artifacts, ledger
# conservation, obs-stream health) with automatic shrinking of any
# finding to a minimal HFREP_FAULTS repro.  Seeded + a deterministic
# --min-schedules coverage floor, so the gate's verdict is
# reproducible; the budget only lets a longer soak explore further.
# HFREP_CHAOS_MIN/HFREP_CHAOS_BUDGET shrink the floor for callers on
# a tight clock (tests/test_analysis_self.py runs this whole script
# inside tier-1 and passes a small floor; the default is the full
# 25-schedule gate).  Env-stripped + CPU-pinned like the others.
skip_gate chaos || \
env -u HFREP_OBS_DIR -u HFREP_HISTORY -u HFREP_FAULTS -u HFREP_HEALTH JAX_PLATFORMS=cpu \
    python -m hfrep_tpu.resilience chaos --seed 11 \
    --budget-secs "${HFREP_CHAOS_BUDGET:-60}" \
    --min-schedules "${HFREP_CHAOS_MIN:-25}" \
    --fixture-seeds 2 --replay-corpus 1>&2
