#!/usr/bin/env bash
# Static-analysis gate: JAX-aware lint + shape contracts over the whole
# tree.  Exit 0 = clean (fixed, # noqa'd, or baselined in
# hfrep_tpu/analysis/baseline.json); exit 1 = new violations; 2 = usage.
#
#   tools/check.sh              # human output
#   tools/check.sh --format json
#
# Also runs inside tier-1 via tests/test_analysis_self.py, so CI fails
# on new violations even when this script isn't invoked directly.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m hfrep_tpu.analysis check \
    hfrep_tpu tools tests bench.py bench_extra.py "$@"
