"""The ONE on-chip profiling instrument (ISSUE 13 consolidation).

``tools/mfu_trace_probe.py`` (profiler cross-check of the analytic MFU
numbers) and ``tools/sp_profile_probe.py`` (staged fwd/grad/gp2 timing
of the sequence-parallel gap) each grew their own trace parsing and
timing scaffolding; both are now subcommands of this probe, built on
the perf microscope (:mod:`hfrep_tpu.obs.attrib`): the trace-event
parsing, interval-union busy accounting and per-op tables are the SAME
code ``obs profile`` runs over a run dir's captured artifacts, and each
traced program additionally lands its lowered-HLO fingerprint +
cost_analysis in the active obs run (when ``HFREP_OBS_DIR`` is set) so
a probe session is diffable against a training run's programs.

    python tools/perf_probe.py mfu [--log-dir DIR]
    python tools/perf_probe.py sp  [--reps 20] [--backend xla|pallas]

The historical entry points keep working as thin shims
(``tools/mfu_trace_probe.py``, ``tools/sp_profile_probe.py`` — the
PR-6 ``bench_bf16_kernel_probe`` pattern).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hfrep_tpu.obs import attrib, timeline
from hfrep_tpu.obs.attrib import interval_union_s, load_trace_events

# module top on purpose: a broken shim must fail BEFORE an expensive
# traced on-chip session, not after (the mfu probe's hard-won rule)
from tools.flops_accounting import HP, epoch_flops  # noqa: E402


def _latest_trace(log_dir: str):
    paths = glob.glob(os.path.join(log_dir,
                                   "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        raise SystemExit(f"no perfetto trace emitted under {log_dir} — "
                         "this platform's profiler exported nothing; the "
                         "cross-check cannot run here")
    return max(paths, key=os.path.getmtime)


# ----------------------------------------------------------------- mfu
def calibrate(log_dir: str, k: int = 50, n: int = 2048) -> dict:
    """Known-FLOPs matmul chain: wall vs trace-derived device time."""
    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)

    @jax.jit
    def chain(a, b):
        def body(c, _):
            return (c @ b) / jnp.float32(n), None
        out, _ = jax.lax.scan(body, a, None, length=k)
        return out

    attrib.profile_jitted(chain, "perf_probe:calibration", a, b)
    jax.device_get(chain(a, b))                           # compile + warm
    t0 = timeline.clock()
    jax.device_get(chain(a * 1.0001, b))
    wall = timeline.clock() - t0
    with jax.profiler.trace(log_dir):
        jax.device_get(chain(a * 1.0002, b))
    events, threads = load_trace_events(_latest_trace(log_dir))
    busy = interval_union_s(events)
    flops = 2.0 * k * n ** 3
    return {"matmul_wall_s": wall, "matmul_trace_busy_s": busy,
            "trace_vs_wall": busy / wall if wall else None,
            "wall_tflops": flops / wall / 1e12,
            "trace_tflops": (flops / busy / 1e12) if busy else None,
            "thread_names": threads}


def epoch_trace(log_dir: str) -> dict:
    """ONE flagship train epoch under the profiler, reconciled against
    an untraced 50-epoch steady block (the bench discipline)."""
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.states import init_gan_state
    from hfrep_tpu.train.steps import make_multi_step, make_train_step

    mcfg = ModelConfig(family="mtss_wgan_gp")             # flagship (48, 35)
    key = jax.random.PRNGKey(0)
    dataset = jax.random.uniform(key, (512, mcfg.window, mcfg.features))
    pair = build_gan(mcfg)

    tcfg50 = TrainConfig(batch_size=32, steps_per_call=50)
    state = init_gan_state(jax.random.PRNGKey(1), mcfg, tcfg50, pair)
    multi = make_multi_step(pair, tcfg50, dataset)
    attrib.profile_jitted(multi, "perf_probe:multi_step_50", state,
                          jax.random.PRNGKey(2))
    state, m = multi(state, jax.random.PRNGKey(2))        # compile + warm
    float(jax.device_get(m["d_loss"]).reshape(-1)[-1])
    t0 = timeline.clock()
    state, m = multi(state, jax.random.PRNGKey(3))
    float(jax.device_get(m["d_loss"]).reshape(-1)[-1])
    steady_epoch_wall = (timeline.clock() - t0) / 50

    tcfg1 = TrainConfig(batch_size=32, steps_per_call=1)
    st1 = init_gan_state(jax.random.PRNGKey(4), mcfg, tcfg1, pair)
    step = jax.jit(make_train_step(pair, tcfg1, dataset))
    attrib.profile_jitted(step, "perf_probe:train_step", st1,
                          jax.random.PRNGKey(5))
    st1, m1 = step(st1, jax.random.PRNGKey(5))            # compile + warm
    float(jax.device_get(m1["d_loss"]))
    with jax.profiler.trace(log_dir):
        st1, m1 = step(st1, jax.random.PRNGKey(6))
        float(jax.device_get(m1["d_loss"]))
    events, _ = load_trace_events(_latest_trace(log_dir))
    busy = interval_union_s(events)
    # pallas kernels surface as custom-calls named after the traced fn;
    # region accounting is the shared interval-union (nested events —
    # the same trap as the total)
    kern = interval_union_s(
        [e for e in events if "LSTM" in e[0] or "lstm" in e[0]])
    top = attrib.op_table(events, top=12)
    out = {"steady_epoch_wall_s": steady_epoch_wall,
           "trace_busy_s": busy,
           "busy_frac_of_steady_wall": busy / steady_epoch_wall,
           "lstm_op_span_s": kern,
           "lstm_share_of_busy": kern / busy if busy else None,
           "top_ops_ms": [(r["op"], round(r["total_s"] * 1e3, 3))
                          for r in top]}
    ex, lo = epoch_flops(48, 35, HP), epoch_flops(48, 35, 100)
    out["analytic_executed_gflops"] = ex / 1e9
    out["analytic_model_gflops"] = lo / 1e9
    if busy:
        out["device_tflops_executed"] = ex / busy / 1e12
        out["device_tflops_model"] = lo / busy / 1e12
    out["wall_tflops_model"] = lo / steady_epoch_wall / 1e12
    return out


def mfu_main(args) -> int:
    out = {"calibration": calibrate(os.path.join(args.log_dir, "cal"))}
    out["epoch"] = epoch_trace(os.path.join(args.log_dir, "epoch"))
    print(json.dumps(out, indent=2))
    return 0


# ------------------------------------------------------------------ sp
def sp_main(args) -> int:
    """Locate where the single-device sequence-parallel step's ~100× gap
    vs the plain step comes from (RESULTS.md honest-bounds note): fwd /
    grad / gp2 stages, state-threaded reps inside one jitted dispatch —
    the only trustworthy timing through the tunnel."""
    from hfrep_tpu.config import ModelConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.parallel.mesh import make_mesh
    from hfrep_tpu.parallel.sequence import sp_critic

    reps = args.reps
    mesh = make_mesh()
    mcfg = ModelConfig(family="mtss_wgan_gp", hidden=100, window=168,
                       features=36)
    pair = build_gan(mcfg)
    key = jax.random.PRNGKey(0)
    x = jax.random.uniform(key, (32, 168, 36))
    d_params = pair.discriminator.init(key, x)["params"]
    be = args.backend

    def plain_apply(p, xx):
        return pair.discriminator.apply({"params": p}, xx, backend=be)

    def sp_apply(p, xx):
        return sp_critic(p, xx, mesh, backend=be)

    def chain(stage, apply):
        """One dispatch = `reps` data-dependent repetitions of `stage`."""
        def scalar(p, xx):
            return jnp.sum(apply(p, xx) ** 2)

        if stage == "fwd":
            unit = lambda p, xx: jnp.sum(apply(p, xx))
        elif stage == "grad":
            unit = lambda p, xx: sum(
                jnp.sum(t) for t in jax.tree_util.tree_leaves(
                    jax.grad(scalar)(p, xx)))
        else:  # gp2: d/dp of ||grad_x scalar||² — the GP second-order shape
            def gp(p, xx):
                g = jax.grad(scalar, argnums=1)(p, xx)
                return jnp.sum(g ** 2)
            unit = lambda p, xx: sum(
                jnp.sum(t) for t in jax.tree_util.tree_leaves(
                    jax.grad(gp)(p, xx)))

        def run(p, xx):
            def body(c, _):
                v = unit(p, xx + 1e-9 * c)     # data dependence across reps
                return v.astype(jnp.float32), None
            out, _ = jax.lax.scan(body, jnp.float32(0), None, length=reps)
            return out

        return jax.jit(run)

    for stage in ("fwd", "grad", "gp2"):
        row = {}
        for name, apply in (("plain", plain_apply), ("sp", sp_apply)):
            f = chain(stage, apply)
            attrib.profile_jitted(f, f"perf_probe:sp:{stage}:{name}",
                                  d_params, x)
            t_c0 = timeline.clock()
            float(f(d_params, x))                       # compile + run
            compile_s = timeline.clock() - t_c0
            t0 = timeline.clock()
            float(f(d_params, x * 1.0001))
            row[name] = (timeline.clock() - t0) / reps
            print(f"  {stage:4s} {name:5s}: {row[name]*1e3:8.2f} ms/unit "
                  f"(compile {compile_s:.0f}s)")
        print(f"{stage}: sp/plain = {row['sp']/row['plain']:.1f}x")
    return 0


# ----------------------------------------------------------------- CLI
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/perf_probe.py",
        description="consolidated on-chip profiling instrument "
                    "(mfu cross-check / sp gap stages)")
    sub = ap.add_subparsers(dest="command", required=True)
    m = sub.add_parser("mfu", help="profiler cross-check of the analytic "
                                   "MFU numbers (VERDICT r4 item 6)")
    m.add_argument("--log-dir", default="/tmp/mfu_trace")
    s = sub.add_parser("sp", help="fwd/grad/gp2 staging of the "
                                  "sequence-parallel gap")
    s.add_argument("--reps", type=int, default=20)
    s.add_argument("--backend", default="xla", choices=["xla", "pallas"])
    args = ap.parse_args(argv)
    return {"mfu": mfu_main, "sp": sp_main}[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
