"""Demonstrate — not assert — the sp capacity win (VERDICT r4 item 4).

Three measurements:

A. **Plain-step HBM boundary, real chip.**  Geometric + binary search of
   the max trainable window W for the single-device flagship train step
   (batch 32, n_critic 5, exact GP — the full epoch program), using the
   compiled program's ``memory_analysis()`` (AOT, no execution) against
   the chip's HBM, then one real execution at the found boundary and one
   expected-OOM probe just above it.

B. **sp per-chip projection.**  The same memory analysis as a function
   of W is ~affine (activations scale with W); a D-chip sp mesh holds
   W/D timesteps per chip plus pipeline carries, so the projected sp
   boundary is ≈ D x (A) at M=1.  The fit and projection are printed
   with the raw points so the extrapolation is auditable.

C. **Execution proof past the single-chip wall.**  On an 8-virtual-
   device CPU mesh (the same mechanism the driver's dryrun uses), run
   REAL sp train steps at a W ABOVE the single-chip boundary from (A) —
   the window axis is genuinely sharded 8 ways, so each device's buffers
   are W/8-sized; host RAM (125 GB) stands in for 8 chips' HBM.

Usage:
  python tools/sp_capacity_probe.py search     # phases A+B (real chip)
  python tools/sp_capacity_probe.py confirm W  # one real run at W (chip)
  python tools/sp_capacity_probe.py spcpu W    # phase C (CPU mesh, set
                                               # JAX_PLATFORMS=cpu + 8 devices)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if len(sys.argv) > 1 and sys.argv[1] == "spcpu":
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

import jax

if len(sys.argv) > 1 and sys.argv[1] == "spcpu":
    # sitecustomize pins JAX_PLATFORMS=axon; config.update wins
    # (tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from hfrep_tpu.config import ModelConfig, TrainConfig
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.train.states import init_gan_state

F, H, B = 36, 100, 32
HBM_BYTES = 16 * 1024**3        # v5e: 16 GiB per chip


def _is_oom(e: BaseException) -> bool:
    """True only for XLA's RESOURCE_EXHAUSTED compile/runtime failure.

    The search loops must treat ONLY out-of-memory as "doesn't fit":
    swallowing every exception as fits=False silently biased the located
    memory wall downward whenever the probe hit a genuine bug (ADVICE
    round 5) — those must propagate.  Matched structurally (class name +
    status string) because ``XlaRuntimeError``'s import path moves
    between jaxlib versions.
    """
    for cls in type(e).__mro__:
        if cls.__name__ == "XlaRuntimeError":
            return "RESOURCE_EXHAUSTED" in str(e)
    return False


def _build(w: int):
    mcfg = ModelConfig(family="mtss_wgan_gp", window=w, features=F, hidden=H)
    tcfg = TrainConfig(batch_size=B, steps_per_call=1)
    dataset = jax.random.uniform(jax.random.PRNGKey(0), (B, w, F), jnp.float32)
    pair = build_gan(mcfg)
    state = init_gan_state(jax.random.PRNGKey(1), mcfg, tcfg, pair)
    return mcfg, tcfg, dataset, pair, state


def plain_step_memory(w: int) -> dict:
    """Compiled (not executed) memory analysis of the plain train step."""
    from hfrep_tpu.train.steps import make_train_step

    mcfg, tcfg, dataset, pair, state = _build(w)
    step = jax.jit(make_train_step(pair, tcfg, dataset), donate_argnums=0)
    compiled = step.lower(state, jax.random.PRNGKey(2)).compile()
    ma = compiled.memory_analysis()
    return {
        "w": w,
        "temp_bytes": int(ma.temp_size_in_bytes),
        "arg_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "total_bytes": int(ma.temp_size_in_bytes + ma.argument_size_in_bytes),
    }


def cmd_search() -> int:
    assert jax.default_backend() == "tpu", "search wants the real chip"
    pts = []
    w = 672
    last_ok = None
    # geometric sweep up
    while True:
        try:
            m = plain_step_memory(w)
        except Exception as e:
            if not _is_oom(e):
                raise               # a genuine bug must not end the sweep
            print(f"W={w}: compile failed (RESOURCE_EXHAUSTED)", flush=True)
            break
        fits = m["total_bytes"] < HBM_BYTES * 0.95
        print(f"W={w}: temp={m['temp_bytes']/2**30:.2f} GiB "
              f"args={m['arg_bytes']/2**30:.2f} GiB fits={fits}", flush=True)
        pts.append(m)
        if not fits:
            break
        last_ok = w
        w *= 2
    if last_ok is None:
        print("nothing fits?!")
        return 1
    # binary refine between last_ok and the first overflow.  At boundary
    # widths XLA's buffer assignment raises RESOURCE_EXHAUSTED from
    # .compile() itself (with a multi-MB allocation dump) rather than
    # returning an analysis — treat a failed compile as "doesn't fit".
    lo, hi = last_ok, w
    while hi - lo > max(64, lo // 50):
        mid = (lo + hi) // 2 // 8 * 8
        try:
            m = plain_step_memory(mid)
            fits = m["total_bytes"] < HBM_BYTES * 0.95
            print(f"W={mid}: temp={m['temp_bytes']/2**30:.2f} GiB fits={fits}",
                  flush=True)
            pts.append(m)
        except Exception as e:
            # ONLY RESOURCE_EXHAUSTED means "doesn't fit"; anything else
            # is a bug that would bias the refined wall downward
            if not _is_oom(e):
                raise
            fits = False
            print(f"W={mid}: compile failed (RESOURCE_EXHAUSTED) fits=False",
                  flush=True)
        if fits:
            lo = mid
        else:
            hi = mid
    # affine fit bytes(W) for the projection
    ws = np.array([p["w"] for p in pts], float)
    bs = np.array([p["total_bytes"] for p in pts], float)
    slope, icept = np.polyfit(ws, bs, 1)
    proj = {d: int((HBM_BYTES * 0.95 - icept) / slope * d) for d in (2, 4, 8)}
    out = {"plain_max_w": lo, "first_overflow_w": hi,
           "bytes_per_w": slope, "fixed_bytes": icept,
           "hbm_bytes": HBM_BYTES, "points": pts,
           "sp_projected_max_w": proj}
    from hfrep_tpu.utils.checkpoint import atomic_text
    atomic_text("results/sp_capacity.json", json.dumps(out, indent=2))
    print(json.dumps({k: out[k] for k in
                      ("plain_max_w", "first_overflow_w", "sp_projected_max_w")}))
    return 0


def cmd_confirm(w: int) -> int:
    """One REAL executed train step at W (expect success at the boundary,
    RESOURCE_EXHAUSTED above it)."""
    from hfrep_tpu.train.steps import make_train_step

    mcfg, tcfg, dataset, pair, state = _build(w)
    step = jax.jit(make_train_step(pair, tcfg, dataset), donate_argnums=0)
    try:
        state, metrics = step(state, jax.random.PRNGKey(3))
        d = float(jax.device_get(metrics["d_loss"]))
        print(json.dumps({"w": w, "ran": True, "d_loss": d}))
    except Exception as e:
        print(json.dumps({"w": w, "ran": False,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}))
    return 0


def cmd_spcpu(w: int, microbatches: int = 8) -> int:
    """Phase C: real sp training steps at W on the 8-virtual-device mesh —
    every window buffer genuinely sharded W/8 per device.

    ``microbatches`` is a retired knob of the manual pipeline (its M=8
    CPU-watchdog workaround and M-independence pins went with it —
    git history).  Since the mesh refactor (ISSUE 15) the launch is the
    unified pjit path: GSPMD lays out the window-sharded step itself,
    there is no superstep schedule to tune, and the knob is accepted
    and ignored by ``make_sp_train_step`` for source compatibility."""
    from jax.sharding import Mesh

    from hfrep_tpu.parallel.sequence import make_sp_train_step

    assert len(jax.devices()) == 8, "run with xla_force_host_platform_device_count=8"
    mcfg, tcfg, dataset, pair, state = _build(w)
    # sp_remat is RETIRED with the manual pipeline (ISSUE 15) — the
    # unified launch ignores it, so the big-W phases re-measure the
    # PLAIN scan's residual footprint (~5.4 GB per 1000 window
    # timesteps measured pre-migration; the W=24192/37632 OOM kills in
    # RESULTS.md were the unrematerialized numbers too).  Kept set so a
    # future GSPMD-era remat re-arms this probe unchanged.
    import dataclasses
    tcfg = dataclasses.replace(tcfg, sp_remat=True)
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("sp",))
    step = make_sp_train_step(pair, tcfg, dataset, mesh,
                              microbatches=microbatches)
    state, metrics = step(state, jax.random.PRNGKey(4))
    d = float(jax.device_get(metrics["d_loss"]))
    print(json.dumps({"w": w, "sp_devices": 8, "microbatches": microbatches,
                      "ran": True, "d_loss": d,
                      "per_device_window": w // 8}))
    return 0


if __name__ == "__main__":
    cmd = sys.argv[1] if len(sys.argv) > 1 else "search"
    if cmd == "search":
        raise SystemExit(cmd_search())
    if cmd == "confirm":
        raise SystemExit(cmd_confirm(int(sys.argv[2])))
    if cmd == "spcpu":
        m = int(sys.argv[3]) if len(sys.argv) > 3 else 8
        raise SystemExit(cmd_spcpu(int(sys.argv[2]), m))
    print(__doc__)
    raise SystemExit(2)
