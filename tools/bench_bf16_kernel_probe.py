"""Shim: the round-4 kernel probe folded into the consolidated
policy-aware probe (ISSUE 6) — one instrument, the production ``Policy``
path instead of hand-rolled casts.  Kept so RESULTS.md's historical
command lines keep working; use ``tools/bench_bf16_probe.py`` directly.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_bf16_probe import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
