"""Round-4 decision probe: bf16 operand streams through the FULL kernel
path (fwd/bwd/adjoint, single-layer + fused stack) vs the round-3 rows.

Extends the round-3 crossover table (RESULTS.md "bf16: measured
decision") with the bf16/pallas column that round 3 called "an essay
rather than a feature", and records where the shape-aware
`kernel_eligible` routes each config (H=512 f32 now falls back to scan
instead of the round-3 VMEM OOM).  Same state-threaded end-to-end
methodology: 50-epoch scanned blocks, TWO warmups (compile + the
donated-state retrace), distinct keys per call.

Usage: python tools/bench_bf16_kernel_probe.py [h1,h2,...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def probe(cases, n_calls=6):
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.states import init_gan_state
    from hfrep_tpu.train.steps import make_multi_step

    data = jax.random.uniform(jax.random.PRNGKey(1), (1000, 48, 35), jnp.float32)
    for h, dtype, backend in cases:
        t_build = time.perf_counter()
        mcfg = ModelConfig(family="mtss_wgan_gp", hidden=h, dtype=dtype)
        tcfg = TrainConfig(steps_per_call=50, lstm_backend=backend)
        pair = build_gan(mcfg)
        state = init_gan_state(jax.random.PRNGKey(0), mcfg, tcfg, pair)
        step = make_multi_step(pair, tcfg, data)
        # keys salted by (h, dtype, backend) so no (program, inputs) pair
        # repeats across configs (server-side execution dedup); the fence
        # is a device_get of the final metrics — block_until_ready does
        # not reliably fence on this backend (RESULTS.md measurement
        # traps), but the calls are state-threaded so materializing the
        # last loss forces the whole chain.
        salt = hash((h, dtype, backend)) % (2**31)
        try:
            state, m = step(state, jax.random.fold_in(jax.random.PRNGKey(1), salt))
            float(jax.device_get(m["d_loss"])[-1])
            state, m = step(state, jax.random.fold_in(jax.random.PRNGKey(99), salt))
            float(jax.device_get(m["d_loss"])[-1])
        except Exception as e:  # noqa: BLE001 - report any compile/run failure
            print(f"h={h} {dtype}/{backend}: FAILED {type(e).__name__}: "
                  f"{str(e)[:140]}", flush=True)
            continue
        t0 = time.perf_counter()
        for i in range(n_calls):
            state, m = step(state, jax.random.fold_in(
                jax.random.PRNGKey(2 + salt), i))
        float(jax.device_get(m["d_loss"])[-1])
        rate = n_calls * 50 / (time.perf_counter() - t0)
        fin = bool(jnp.isfinite(m["d_loss"]).all())
        print(f"h={h} {dtype}/{backend}: {rate:.1f} steps/s finite={fin} "
              f"(total {time.perf_counter() - t_build:.0f}s incl. compile)",
              flush=True)


if __name__ == "__main__":
    hiddens = ([int(v) for v in sys.argv[1].split(",")] if len(sys.argv) > 1
               else [100, 256, 384, 512])
    cases = []
    for h in hiddens:
        cases += [(h, "bfloat16", "pallas"), (h, "float32", "pallas")]
    probe(cases)
    print("probe done", flush=True)
