"""Async boundary engine probe: overlap_frac at the two drive boundaries.

ROADMAP item 2(a)'s after-measurement.  The wall-clock ledger (ISSUE 18)
pinned ``timeline/overlap_frac`` — the fraction of boundary-relevant
host time overlapping device execution, Σhost/(Σhost+Σsync) over steady
windows — at exactly two boundaries: the chunked-AE chunk stops
(``ae_chunk``) and the GAN block stops (``gan_block``), with baseline
rows ``TL18_*`` committed to ``hfrep_tpu/obs/_bench_history/``.  The
async boundary engine (ISSUE 19) is supposed to move that number: the
AE drive's continue/stop read-back became a one-slot pending future
(the host syncs one chunk behind the device), the GAN block loop
commits staged checkpoint writes after the next dispatch, and both
drives' ledger windows still flush at the syncs they already pay.

This probe re-drives both boundaries at the TL18 shapes and records the
after-rows:

* **gan_block** — a ``family="gan"`` trainer at w24f16h48b32 (the
  TL18_gan_block comparability key) through the pipelined block loop;
* **ae_chunk** — a chunked AE latent sweep through the deferred-flag
  drive (un-annotated, like TL18_ae_chunk: the AE engine is not a
  model-config run, so its key is the null family/shape series).

Each leg runs in its own obs session, re-emits the session's closing
``timeline/overlap_frac`` / ``attrib/dispatch_frac`` as
``bench/overlap_{gan_block,ae_chunk}`` (explicit direction-"up"
``regress.DEFAULT_THRESHOLDS`` rows — HF001), and gates + ingests
against the committed history store, so the overlap series accumulates
next to its TL18 baselines.  On the 1-core CPU CI container the
gan_block number is structural (≈1.0 — a synchronous backend overlaps
everything by definition); the ae_chunk number is the real needle: the
eager boundary sync measured 0.78 there, the deferred sync should park
the host on an already-resolved flag.

``--self-test`` asserts the engine's *contract* instead of gating
history: serial-vs-double-buffered bit-identity on an early-stop
fixture, the one-chunk-overshoot accounting, and an overlap_frac floor
for the deferred drive — including a synthetic leg that injects
deterministic host-side sleeps into every chunk dispatch and checks
the floor still holds — all in throwaway obs sessions (never ingested).

Prints ONE JSON line.  Exit 0 = ok, 1 = self-check failure or history
regression, 2 = tooling failure.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile

if __name__ == "__main__":                   # `python tools/bench_overlap.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import hfrep_tpu.obs as obs_pkg
from hfrep_tpu.obs import timeline

#: self-test floor for the deferred-flag drive's overlap fraction.  The
#: pending future is resolved by the time the host syncs it (the sync
#: parks on a scalar the previous chunk already produced), so the
#: steady-window sync share is microseconds against a multi-ms wall —
#: 0.90 leaves an order of magnitude of headroom for a preempted host.
SELF_OVERLAP_FLOOR = 0.90


def _overlap_gauges(obs):
    """The session's closing overlap numbers (None while telemetry is
    off or before the first steady window flush)."""
    return (obs.gauge("timeline/overlap_frac").value,
            obs.gauge("attrib/dispatch_frac").value)


# ------------------------------------------------------------ gan_block
def _gan_leg(obs, self_test: bool) -> dict:
    """Drive the pipelined GAN block loop and read the boundary's
    ledger.  Full mode reproduces the TL18_gan_block recipe exactly
    (same rng stream, same config → same w24f16h48b32 comparability
    key); the self-test shrinks the schedule but keeps the shape."""
    import jax.numpy as jnp

    from hfrep_tpu.config import ExperimentConfig, ModelConfig, TrainConfig
    from hfrep_tpu.train.trainer import GanTrainer

    epochs, log_every = (60, 20) if self_test else (400, 100)
    cfg = ExperimentConfig(
        model=ModelConfig(family="gan", features=16, window=24, hidden=48),
        train=TrainConfig(epochs=epochs, batch_size=32, n_critic=2,
                          steps_per_call=1, log_every=log_every))
    g = np.random.default_rng(7)
    data = jnp.asarray(g.uniform(0, 1, (256, 24, 16)).astype(np.float32))
    t0 = timeline.clock()
    trainer = GanTrainer(cfg, data)
    trainer.train(epochs=epochs)
    wall_s = timeline.clock() - t0
    overlap, dispatch_frac = _overlap_gauges(obs)
    if overlap is not None:
        obs.gauge("bench/overlap_gan_block").set(float(overlap))
    return {"wall_s": round(wall_s, 4),
            "steps_per_sec": round(float(trainer.steps_per_sec), 3),
            "overlap_frac": overlap, "dispatch_frac": dispatch_frac}


# ------------------------------------------------------------- ae_chunk
def _ae_leg(obs, self_test: bool) -> dict:
    """Drive the chunked AE through the deferred-flag engine and read
    the chunk boundary's ledger.  Full mode reproduces the TL18_ae_chunk
    recipe exactly (same rng stream, same config, same key), and is
    deliberately un-annotated like the baseline: the AE engine is not a
    model-config run, so it keys into the null-family/shape series.
    patience == epochs keeps every chunk boundary in play (the steady
    windows measure the boundary sync, not the early-exit economics —
    bench_ae.py owns those)."""
    import jax
    import jax.numpy as jnp

    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication import engine

    if self_test:
        rows, feats, latent, epochs, chunk, batch = 96, 6, 4, 60, 10, 16
    else:
        rows, feats, latent, epochs, chunk, batch = 400, 16, 6, 120, 10, 64
    cfg = AEConfig(n_factors=feats, latent_dim=latent, epochs=epochs,
                   chunk_epochs=chunk, patience=epochs, batch_size=batch)
    g = np.random.default_rng(3)
    x = jnp.asarray(g.uniform(0, 1, (rows, feats)).astype(np.float32))
    t0 = timeline.clock()
    _, stats = engine.train_autoencoder_chunked(jax.random.PRNGKey(2), x, cfg)
    wall_s = timeline.clock() - t0
    overlap, dispatch_frac = _overlap_gauges(obs)
    if overlap is not None:
        obs.gauge("bench/overlap_ae_chunk").set(float(overlap))
    return {"wall_s": round(wall_s, 4),
            "chunks": int(stats.chunks_dispatched),
            "overshoot_chunks": int(stats.overshoot_chunks),
            "overlap_frac": overlap, "dispatch_frac": dispatch_frac}


# ---------------------------------------------------- synthetic (sleep)
def _sleep_leg(obs, self_test: bool) -> dict:
    """Deterministic sleep-injected host work through the deferred-flag
    drive (the ISSUE 19 CI self-test): each chunk dispatch carries a
    fixed host-side sleep — boundary bookkeeping a serial drive would
    pay in the open.  With the one-slot pending future that work is
    parked behind an in-flight chunk, so ``timeline/overlap_frac`` must
    hold the floor even though the injected host time dwarfs the device
    work; a re-serialized boundary (the HF010 class) fails the floor."""
    import time

    import jax
    import jax.numpy as jnp

    from hfrep_tpu.replication import engine

    epochs, chunk_epochs, sleep_s = 40, 5, 0.002

    @jax.jit
    def _device_chunk(carry, ks):
        def body(c, k):
            c = c * 0.999 + jnp.sum(k) * 1e-6
            loss = jnp.sum(c * c)
            return c, (loss, loss * 0.5, jnp.zeros((), jnp.bool_))
        w, (tl, vl, stop) = jax.lax.scan(body, carry[0], ks)
        return (w, carry[1], carry[2], carry[3], carry[4]), (tl, vl, stop)

    def chunk_fn(carry, ks):
        time.sleep(sleep_s)           # the injected deterministic host work
        return _device_chunk(carry, ks)

    carry = (jnp.ones((8,), jnp.float32), 0, 0, 0,
             jnp.zeros((2,), jnp.bool_))      # carry[4]: never stops
    keys = jnp.zeros((epochs, 2), jnp.float32)
    t0 = timeline.clock()
    _, _, pos, chunks, overshoot = engine._drive_chunks(
        chunk_fn, carry, keys, epochs, chunk_epochs)
    wall_s = timeline.clock() - t0
    overlap, dispatch_frac = _overlap_gauges(obs)
    return {"wall_s": round(wall_s, 4), "epochs": int(pos),
            "chunks": int(chunks), "overshoot_chunks": int(overshoot),
            "sleep_ms_per_chunk": sleep_s * 1e3,
            "overlap_frac": overlap, "dispatch_frac": dispatch_frac}


# ------------------------------------------------------------ self-test
def _contract_checks() -> list:
    """The engine's determinism contract, asserted without telemetry:
    double-buffered dispatch must change WHEN the host syncs, never
    WHAT the drive computes."""
    import jax

    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.replication import engine

    import jax.numpy as jnp

    problems = []
    g = np.random.default_rng(3)
    x = jnp.asarray(g.standard_normal((96, 6)).astype(np.float32))
    # lr=0 freezes the params so every lane's val loss plateaus and
    # patience fires deterministically early — the overshoot fixture
    cfg = AEConfig(n_factors=6, latent_dim=4, epochs=120, batch_size=16,
                   patience=5, seed=0, chunk_epochs=15, lr=0.0)
    key = jax.random.PRNGKey(cfg.seed)
    res_db, st_db = engine.train_autoencoder_chunked(key, x, cfg)
    res_se, st_se = engine.train_autoencoder_chunked(
        key, x, dataclasses.replace(cfg, double_buffer=False))
    same = jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b),
                                    equal_nan=True), res_db, res_se))
    if not same:
        problems.append("double-buffered result diverged from the serial "
                        "drive (bit-identity contract broken)")
    if st_db.chunks_dispatched != st_se.chunks_dispatched + 1:
        problems.append(
            f"expected exactly one overshoot chunk on the early-stop "
            f"fixture, got db={st_db.chunks_dispatched} vs "
            f"serial={st_se.chunks_dispatched}")
    if st_db.overshoot_chunks != 1 or st_se.overshoot_chunks != 0:
        problems.append(
            f"overshoot accounting wrong: db={st_db.overshoot_chunks} "
            f"(want 1), serial={st_se.overshoot_chunks} (want 0)")
    return problems


def run_probe(obs_root: str, self_test: bool, ingest: bool) -> int:
    prefix = "selftest" if self_test else "OV19"
    problems = []
    if self_test:
        problems += _contract_checks()

    plan = [("gan_block", _gan_leg), ("ae_chunk", _ae_leg)]
    if self_test:
        plan.append(("synthetic", _sleep_leg))
    legs = {}
    run_dirs = []
    for name, leg in plan:
        run_dir = os.path.join(obs_root, f"{prefix}_{name}")
        with obs_pkg.session_or_off(run_dir, "bench_overlap",
                                    command="bench_overlap") as obs:
            legs[name] = leg(obs, self_test)
            if obs.enabled:
                run_dirs.append(run_dir)
            obs.memory_snapshot(phase=f"bench_overlap_{name}_end")

    for name in legs:
        ov = legs[name]["overlap_frac"]
        if ov is None:
            problems.append(f"{name}: no steady ledger window flushed "
                            "(overlap_frac never measured)")
        elif self_test and ov < SELF_OVERLAP_FLOOR:
            problems.append(f"{name}: overlap_frac {ov:.4f} below the "
                            f"{SELF_OVERLAP_FLOOR} self-test floor — the "
                            "boundary re-serialized")

    out = {"metric": "boundary_overlap_frac"}
    out.update(legs)
    out["self_check"] = "ok" if not problems else "; ".join(problems)
    out["self_test"] = bool(self_test)
    print(json.dumps(out))
    rc = 0
    if problems:
        print(f"bench_overlap: SELF-CHECK FAILED: {'; '.join(problems)}",
              file=sys.stderr)
        rc = 1
    if ingest and not self_test:
        # gate each leg's run against its own TL18_* baseline series and
        # ingest the after-row — the committed store is the ROADMAP
        # item 2(a) record of what the engine moved
        from hfrep_tpu.obs import history as hist_mod
        for run_dir in run_dirs:
            hist = hist_mod.resolve_history(run_dir)
            if hist:
                rc = hist_mod.gate_and_ingest(run_dir, hist, rc)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_overlap",
        description="async boundary engine overlap probe (ISSUE 19)")
    ap.add_argument("--self-test", action="store_true",
                    help="tiny shapes: DB-vs-serial bit-identity, "
                         "overshoot accounting and an overlap floor in "
                         "a throwaway session; never touches history")
    args = ap.parse_args(argv)

    obs_root = os.environ.get("HFREP_OBS_DIR")
    if obs_root and not args.self_test:
        return run_probe(obs_root, False, ingest=True)
    # like bench.py since ISSUE 6: without HFREP_OBS_DIR the probe still
    # records into a throwaway run dir, so a bare full run gates +
    # ingests against the repo-default store; the self-test's throwaway
    # sessions are never ingested regardless
    with tempfile.TemporaryDirectory(prefix="hfrep_bench_overlap_") as td:
        return run_probe(td, args.self_test, ingest=not args.self_test)


if __name__ == "__main__":
    sys.exit(main())
