"""Serving-layer load probe: p50/p95 latency + QPS under simulated
concurrent portfolio-replication queries.

The ROADMAP's north-star workload is answering replication queries for
millions of users; this probe measures what the ``hfrep_tpu.serve``
envelope (AOT programs + micro-batching + admission control) actually
sustains on this host, and — more importantly for an overload-protection
layer — that the envelope's *shape* holds at every offered load:

* levels of **1k / 10k / 100k simulated concurrent queries** (each level
  is one open-loop burst offered to the admission layer; everything the
  envelope cannot serve inside the deadline must come back as a typed
  rejection);
* per level: p50/p95 latency of served requests, QPS, shed rate — and
  the structural self-checks: **every submitted request reached exactly
  one terminal outcome** (zero silent drops), zero untyped errors, p95
  bounded even at 100× overload (shed requests cost microseconds, which
  is the whole point of shedding).

``--self-test`` (wired into ``tools/check.sh``, env-stripped) shrinks
the levels and adds the chaos smoke: a deadline storm (every request
offered a ~5ms budget), an overload burst past the admission bound, and
an injected ``io_fail@serve_result`` streak that must trip the circuit
breaker into serving flagged-stale degraded answers and close again
after cooldown.

Prints ONE JSON line.  Exit 0 = self-checks passed, 1 = a check (or a
history regression) failed, 2 = tooling failure.

Telemetry: with ``HFREP_OBS_DIR`` the run lands in an obs run dir with
``serve/*`` gauges (QPS, p50/p95, shed rate, queue depth) plus per-level
``bench/serve_*`` gauges, annotated with a ``serve`` config section so
the history store indexes it under the serving comparability key
(``svb<max_batch><deadline class>``) — serve latency series never blend
into training steps/sec series.  With a history store on top
(``HFREP_HISTORY`` or the repo default), the run gates against the
rolling baseline and auto-ingests on pass, exactly like ``bench.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __name__ == "__main__":                 # `python tools/bench_serve.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

from hfrep_tpu.obs import timeline
import hfrep_tpu.obs as obs_pkg

#: offered-load levels (simulated concurrent queries per burst)
LEVELS = (1_000, 10_000, 100_000)
SELF_TEST_LEVELS = (128, 512)

#: p95 sanity bound, as a multiple of the request deadline: a served
#: request's latency is queue wait (deadline-capped at the batcher) +
#: one batch execution, so p95 beyond a few deadlines means the
#: cancellation machinery rotted
P95_DEADLINE_MULT = 4.0


def _level_label(n: int) -> str:
    return f"c{n // 1000}k" if n >= 1000 else f"c{n}"


def _check_level(level: int, rep: dict, timeout_ms: float, problems: list):
    if rep["terminal"] != rep["submitted"]:
        problems.append(f"{_level_label(level)}: {rep['submitted']} "
                        f"submitted but {rep['terminal']} terminal "
                        "(silent drops)")
    if rep["errors"]:
        problems.append(f"{_level_label(level)}: {rep['errors']} untyped "
                        "outcomes")
    p95 = rep.get("p95_ms")
    if p95 is not None and p95 > P95_DEADLINE_MULT * timeout_ms:
        problems.append(f"{_level_label(level)}: p95 {p95:.1f}ms "
                        f"> {P95_DEADLINE_MULT}x the {timeout_ms:.0f}ms "
                        "deadline")
    if rep["results"] + rep["stale"] == 0:
        problems.append(f"{_level_label(level)}: nothing served at all")


def _chaos_smoke(server, panels, problems: list) -> dict:
    """The shed + deadline + breaker paths, exercised deterministically
    (the full chaos matrix lives in the resilience selftest; this is the
    CI-fast smoke that the bench's own envelope can take a punch)."""
    import hfrep_tpu.resilience as res
    from concurrent.futures import wait
    from hfrep_tpu.serve.loadgen import classify

    # deadline storm: a burst with a ~5ms budget — the batcher must
    # cancel what it cannot dispatch in time, typed
    futs = [server.replicate(panels[i % len(panels)], timeout_ms=5.0)
            for i in range(64)]
    wait(futs, timeout=60)
    storm = classify(futs)
    if storm["deadline"] == 0:
        problems.append("chaos: 5ms-deadline storm produced no misses")

    # breaker: a result-publish EIO streak must trip it into degraded
    # stale answers, and one clean probe after cooldown must close it
    res.install_plan(res.FaultPlan.parse("io_fail@serve_result=1x50"))
    try:
        faults = 0
        for _ in range(6):
            f = server.replicate(panels[0], timeout_ms=5000.0)
            wait([f], timeout=60)
            if f.exception() is not None:
                faults += 1
            if server.breaker.state == "open":
                break
        if server.breaker.state != "open":
            problems.append(f"chaos: {faults} publish faults did not trip "
                            "the breaker")
        probe = server.replicate(panels[1], timeout_ms=5000.0)
        wait([probe], timeout=60)
        if probe.exception() is not None or not probe.result().stale:
            problems.append("chaos: breaker-open answer was not a "
                            "flagged-stale degraded result")
    finally:
        res.clear_plan()
    time.sleep(server.cfg.breaker_cooldown_s + 0.1)
    fresh = server.replicate(panels[0], timeout_ms=5000.0)
    wait([fresh], timeout=60)
    if fresh.exception() is not None or fresh.result().stale:
        problems.append("chaos: post-cooldown probe did not serve fresh")
    if server.breaker.state != "closed":
        problems.append("chaos: breaker did not close after a good probe")
    return {"deadline_misses": storm["deadline"],
            "breaker_trips": server.breaker.trips}


def _trace_drill(server, panels, obs, problems: list) -> dict:
    """Zero-orphan-trace check (the tracing analogue of the
    zero-silent-drop ledger): thread explicit trace IDs through the load
    generator, then assert every submitted request's terminal outcome is
    reachable by ``report --trace`` over the run dir's event stream."""
    from hfrep_tpu.obs.report import has_terminal, trace_index
    from hfrep_tpu.serve.loadgen import drive_load

    rep = drive_load(server, 64, panels, timeout_ms=1000.0,
                     trace_prefix="lg-")
    obs.flush()
    # ONE parse of the run dir indexes every trace (trace_events per ID
    # would re-read the whole stream 64 times)
    index = trace_index([obs.run_dir], rep["trace_ids"])
    orphans = [t for t in rep["trace_ids"]
               if not has_terminal(index.get(t, []))]
    if orphans:
        problems.append(f"traces: {len(orphans)}/{len(rep['trace_ids'])} "
                        f"orphan trace(s) (first: {orphans[0]})")
    # the reconstructed path must attribute the admit hop at minimum
    # (completed requests additionally carry dispatch + complete)
    first = index.get(rep["trace_ids"][0], [])
    if not any(r.get("name") == "serve_admit" for r in first):
        problems.append("traces: reconstruction lacks the admit hop")
    return {"submitted": rep["submitted"], "traced": len(rep["trace_ids"]),
            "orphans": len(orphans)}


def run_probe(obs, self_test: bool) -> int:
    from hfrep_tpu.serve.fixture import fixture_server, warm_server
    from hfrep_tpu.serve.loadgen import drive_load, make_panels
    from hfrep_tpu.serve.server import ServeConfig

    if self_test:
        levels = SELF_TEST_LEVELS
        feats, rows_choices = 8, (16, 24, 32)
        scfg = ServeConfig(max_batch=4, batch_window_ms=3.0,
                           request_timeout_ms=250.0, max_queue=64,
                           workers=1, row_buckets=(32, 64),
                           breaker_failures=2, breaker_cooldown_s=0.3,
                           compile_storm=64)
    else:
        levels = LEVELS
        feats, rows_choices = 16, (32, 64, 96, 128)
        scfg = ServeConfig(max_batch=8, batch_window_ms=5.0,
                           request_timeout_ms=250.0, max_queue=256,
                           workers=2, row_buckets=(64, 128, 256),
                           compile_storm=64, event_log_every=1000)
    # annotate the SERVE envelope (not a training shape): the history
    # key's signature becomes svb<max_batch><deadline class>, its own
    # series — serve p95 can never blend into a steps/sec baseline
    obs.annotate(config={"serve": {"max_batch": scfg.max_batch,
                                   "deadline_ms": scfg.request_timeout_ms,
                                   "max_queue": scfg.max_queue,
                                   "workers": scfg.workers}})

    server = fixture_server(scfg, feats=feats)
    panels = make_panels(11, feats, rows_choices, variants=8)
    problems: list = []
    doc: dict = {"metric": "serve_load", "self_test": bool(self_test)}
    try:
        t0 = timeline.clock()
        warmed = warm_server(server, panels)
        doc["warm_programs"] = warmed
        doc["warm_s"] = round(timeline.clock() - t0, 3)
        doc["aot_export"] = bool(__import__(
            "hfrep_tpu.serve.aot", fromlist=["x"]).jax_export_supported())

        per_level = {}
        for level in levels:
            rep = drive_load(server, level, panels,
                             timeout_ms=scfg.request_timeout_ms, wave=level)
            _check_level(level, rep, scfg.request_timeout_ms, problems)
            label = _level_label(level)
            per_level[label] = {k: rep[k] for k in
                                ("submitted", "results", "stale", "shed",
                                 "deadline", "worker_faults", "invalid",
                                 "errors", "qps", "p50_ms", "p95_ms",
                                 "shed_rate", "wall_s")}
            for name, value in (("qps", rep["qps"]),
                                ("p95_ms", rep["p95_ms"]),
                                ("shed_rate", rep["shed_rate"])):
                if value is not None and np.isfinite(value):
                    obs.gauge(f"bench/serve_{name}_{label}").set(float(value))
        doc["levels"] = per_level

        # headline serve/* gauges from the LOWEST level — the regime
        # where (nearly) everything is served fresh, so p50/p95 measure
        # the envelope, not the shed fast-path
        head = per_level[_level_label(levels[0])]
        for name, value in (("serve/qps", head["qps"]),
                            ("serve/p50_ms", head["p50_ms"]),
                            ("serve/p95_ms", head["p95_ms"]),
                            ("serve/shed_rate", head["shed_rate"])):
            if value is not None and np.isfinite(value):
                obs.gauge(name).set(float(value))
        obs.gauge("serve/queue_depth").set(server.batcher.depth)

        if self_test:
            doc["chaos"] = _chaos_smoke(server, panels, problems)
            if obs.enabled:
                doc["traces"] = _trace_drill(server, panels, obs, problems)
            else:
                problems.append("traces: no run dir to verify traces "
                                "against (self-test wants one)")

        ledger = server.outcomes.as_dict()
        if ledger["terminal"] != ledger["submitted"]:
            problems.append(f"ledger: {ledger['submitted']} submitted vs "
                            f"{ledger['terminal']} terminal (silent drops)")
        doc["ledger"] = ledger
        doc["stats"] = {k: server.stats()[k] for k in ("breaker", "cache")}
        obs.memory_snapshot(phase="bench_serve_end")
    finally:
        server.stop()

    doc["self_check"] = "ok" if not problems else "; ".join(problems)
    print(json.dumps(doc))
    if problems:
        print(f"bench_serve: SELF-CHECK FAILED: {'; '.join(problems)}",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_serve",
        description="serving-layer p50/p95/QPS load probe + chaos smoke")
    ap.add_argument("--self-test", action="store_true",
                    help="tiny levels + the shed/deadline/breaker chaos "
                         "smoke in seconds on CPU (the CI fast path)")
    args = ap.parse_args(argv)

    import contextlib
    import tempfile

    obs_dir = os.environ.get("HFREP_OBS_DIR")
    # the self-test's zero-orphan-trace drill needs a readable event
    # stream even in the env-stripped CI invocation: a throwaway run dir
    # that never gates or ingests (the sentinel keys off HFREP_OBS_DIR
    # alone, so a temp dir cannot pollute the committed store)
    tmp_ctx = (tempfile.TemporaryDirectory(prefix="bench_serve_obs_")
               if args.self_test and not obs_dir
               else contextlib.nullcontext(None))
    with tmp_ctx as tmp_dir:
        run_dir = obs_dir or (os.path.join(tmp_dir, "run")
                              if tmp_dir else None)
        with obs_pkg.session_or_off(run_dir, "bench_serve",
                                    command="bench_serve") as obs:
            if obs_dir and not obs.enabled:
                obs_dir = None             # degraded: nothing to gate below
            rc = run_probe(obs, args.self_test)
        from hfrep_tpu.obs import history as hist_mod
        hist = hist_mod.resolve_history(obs_dir)
        if obs_dir and hist:
            rc = hist_mod.gate_and_ingest(obs_dir, hist, rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
