"""Compatibility shim — the analytic FLOPs/MFU accounting moved into the
package as :mod:`hfrep_tpu.obs.flops` so the telemetry layer can compute
per-step MFU in-process (VERDICT r1 item 3 lives on there; this file
keeps the documented ``python tools/flops_accounting.py [sps ...]``
invocation working).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hfrep_tpu.obs.flops import (  # noqa: F401  (re-exported API)
    B, H, HP, N_CRITIC, PEAK_BF16, PEAK_F32, cf, epoch_flops, gf, main,
    mfu, mfu_series, report,
)

if __name__ == "__main__":
    sys.exit(main())
