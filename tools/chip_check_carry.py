"""On-chip validation of the carry-injection pallas kernels.

The CPU suite runs these kernels in interpret mode
(tests/test_pallas_lstm.py carry tests); this driver compiles them
natively on the real TPU and re-runs the same oracles — forward,
first-order gradients, GP-pattern second order — against the scan twin,
plus the sequence-parallel composition (`sp_lstm(backend='pallas')`
under `shard_map(check_vma=True)`) on a 1-device mesh, the part
interpret mode cannot exercise at all.

Run: `python tools/chip_check_carry.py [--section oracle|sp|train|speed]`
(needs the tunneled TPU; each section adds several ~20-40s tunnel
compiles, so `all` wants ~15 min while one section fits ~5).
Results recorded in RESULTS.md ("sequence-parallel pallas chunks").
"""

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

assert jax.default_backend() == "tpu", "this driver needs the real chip"

from hfrep_tpu.obs import timeline
from hfrep_tpu.ops.pallas_lstm import lstm_seq_carry  # noqa: E402

KEY = jax.random.PRNGKey(42)


def fwd_scan_carry(xz, rec, h0, c0, activation):
    act = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh}[activation]

    def step(carry, xz_t):
        h, c = carry
        z = xz_t + h @ rec
        zi, zf, zc, zo = jnp.split(z, 4, axis=-1)
        c2 = jax.nn.sigmoid(zf) * c + jax.nn.sigmoid(zi) * act(zc)
        h2 = jax.nn.sigmoid(zo) * act(c2)
        return (h2, c2), h2

    (h_f, c_f), hs = jax.lax.scan(step, (h0, c0), xz)
    return hs, c_f


def check(name, got, ref, tol):
    """Scale-normalized comparison: on the real chip both the kernel and
    the scan twin run the MXU's default f32-via-bf16-pass matmuls, so
    they agree to ~1e-3..1e-2 relative (vs a Precision.HIGHEST twin both
    drift by the same class — measured; the comparison that isolates
    kernel correctness is against the same precision regime; the strict
    f32 oracle is the interpret-mode CPU test suite)."""
    got, ref = np.asarray(got), np.asarray(ref)
    scale = float(np.max(np.abs(ref))) or 1.0
    d = float(np.max(np.abs(got - ref))) / scale
    status = "ok" if d <= tol else "FAIL"
    print(f"  {name:24s} rel_err {d:.3e} (scale {scale:.2g})  [{status}]")
    assert d <= tol, name
    return d


def section_oracle():
    w, b, hp = 48, 32, 128        # flagship-like chunk shape
    ks = jax.random.split(KEY, 4)
    xz = 0.3 * jax.random.normal(ks[0], (w, b, 4 * hp))
    rec = 0.3 * jax.random.normal(ks[1], (hp, 4 * hp))
    h0 = 0.5 * jax.random.normal(ks[2], (b, hp))
    c0 = 0.5 * jax.random.normal(ks[3], (b, hp))

    for activation in ("sigmoid", "tanh"):
        print(f"activation={activation}")
        hs, cf = jax.jit(functools.partial(lstm_seq_carry,
                                           activation=activation))(xz, rec, h0, c0)
        ref_hs, ref_cf = fwd_scan_carry(xz, rec, h0, c0, activation)
        check("forward hs", hs, ref_hs, 1e-6)
        check("forward c_fin", cf, ref_cf, 1e-6)

        wts = jax.random.normal(jax.random.fold_in(KEY, 9), (w, b, hp))
        u = jax.random.normal(jax.random.fold_in(KEY, 10), (b, hp))

        def loss(fn, xz, rec, h0, c0):
            hs, c_fin = fn(xz, rec, h0, c0, activation)
            return jnp.sum(hs * wts) + jnp.sum(c_fin * u)

        ref_g = jax.jit(jax.grad(functools.partial(loss, fwd_scan_carry),
                                 argnums=(0, 1, 2, 3)))(xz, rec, h0, c0)
        got_g = jax.jit(jax.grad(functools.partial(loss, lstm_seq_carry),
                                 argnums=(0, 1, 2, 3)))(xz, rec, h0, c0)
        for n, a, r in zip(("dxz", "drec", "dh0", "dc0"), got_g, ref_g):
            check(f"grad {n}", a, r, 1e-2)

        def gp_like(fn, xz, rec, h0, c0):
            def scalar(xzi, h0i, c0i):
                hs, c_fin = fn(xzi, rec, h0i, c0i, activation)
                return jnp.sum(hs) + jnp.sum(c_fin)
            g = jax.grad(scalar, argnums=(0, 1, 2))(xz, h0, c0)
            norms = jnp.sqrt(sum(jnp.sum(t ** 2) for t in g) + 1e-12)
            return (1.0 - norms) ** 2

        for wrt in (0, 1, 2, 3):
            ref2 = jax.jit(jax.grad(functools.partial(gp_like, fwd_scan_carry),
                                    argnums=wrt))(xz, rec, h0, c0)
            got2 = jax.jit(jax.grad(functools.partial(gp_like, lstm_seq_carry),
                                    argnums=wrt))(xz, rec, h0, c0)
            check(f"2nd-order wrt={wrt}", got2, ref2, 1e-2)


def section_sp(mesh, sp_lstm):
    print("sp_lstm backend=pallas (1-device mesh, shard_map check_vma)")
    h, f, bb, ww = 100, 35, 8, 48
    kf = jax.random.split(jax.random.fold_in(KEY, 77), 3)
    kern = 0.3 * jax.random.normal(kf[0], (f, 4 * h))
    recu = 0.3 * jax.random.normal(kf[1], (h, 4 * h))
    bias = 0.1 * jax.random.normal(kf[2], (4 * h,))
    x = jax.random.normal(jax.random.fold_in(KEY, 78), (bb, ww, f))
    ref = sp_lstm(kern, recu, bias, x, mesh, activation="sigmoid")
    got = sp_lstm(kern, recu, bias, x, mesh, activation="sigmoid",
                  backend="pallas")
    check("sp pallas vs xla", got, ref, 1e-5)  # forward: same rounding

    def sp_loss(be, kern, recu, bias):
        out = sp_lstm(kern, recu, bias, x, mesh, activation="sigmoid",
                      backend=be)
        return jnp.sum(out ** 2)

    rg = jax.grad(functools.partial(sp_loss, "xla"), argnums=(0, 1, 2))(
        kern, recu, bias)
    gg = jax.grad(functools.partial(sp_loss, "pallas"), argnums=(0, 1, 2))(
        kern, recu, bias)
    for n, a, r in zip(("kernel", "recurrent", "bias"), gg, rg):
        check(f"sp grad {n}", a, r, 1e-2)

    # fused 2-layer pipeline (sp_lstm2 via sp_critic) with pallas chunks
    from hfrep_tpu.config import ModelConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.parallel.sequence import sp_critic

    pair = build_gan(ModelConfig(family="mtss_wgan_gp", hidden=h,
                                 window=ww, features=f))
    d_params = pair.discriminator.init(KEY, x)["params"]
    check("sp2 critic fwd", sp_critic(d_params, x, mesh, backend="pallas"),
          sp_critic(d_params, x, mesh), 1e-4)

    def critic_loss(be, p):
        return jnp.sum(sp_critic(p, x, mesh, backend=be) ** 2)

    cg_ref = jax.grad(functools.partial(critic_loss, "xla"))(d_params)
    cg_got = jax.grad(functools.partial(critic_loss, "pallas"))(d_params)
    err = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        / (float(np.max(np.abs(np.asarray(b)))) or 1.0)
        for a, b in zip(jax.tree_util.tree_leaves(cg_got),
                        jax.tree_util.tree_leaves(cg_ref)))
    status = "ok" if err <= 1e-2 else "FAIL"
    print(f"  {'sp2 critic grads':24s} rel_err {err:.3e}  [{status}]")
    assert err <= 1e-2


def section_train(mesh):
    """Full sp TRAINING step (n_critic GP critic updates + generator
    update) with pallas chunks — the round-2 deferral, now live."""
    print("make_sp_train_step lstm_backend=pallas (flagship family)")
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.parallel.sequence import make_sp_train_step
    from hfrep_tpu.train.states import init_gan_state

    mcfg = ModelConfig(family="mtss_wgan_gp", hidden=16, window=48, features=5)
    dataset = jax.random.uniform(jax.random.PRNGKey(5), (32, 48, 5))
    pair = build_gan(mcfg)
    states, metrics = {}, {}
    for be in ("xla", "pallas"):
        tcfg = TrainConfig(batch_size=8, n_critic=2, lstm_backend=be)
        state = init_gan_state(jax.random.PRNGKey(6), mcfg, tcfg, pair)
        step = make_sp_train_step(pair, tcfg, dataset, mesh)
        states[be], metrics[be] = step(state, jax.random.PRNGKey(7))
    check("sp train d_loss", metrics["pallas"]["d_loss"],
          metrics["xla"]["d_loss"], 1e-3)
    check("sp train g_loss", metrics["pallas"]["g_loss"],
          metrics["xla"]["g_loss"], 1e-3)
    leaf = lambda s: jax.tree_util.tree_leaves(s.g_params)[0]
    check("sp train g_params", leaf(states["pallas"]), leaf(states["xla"]),
          1e-3)

    # dp×sp MANUAL mode with the carry kernels, on real hardware: a 1×1
    # ('dp','sp') mesh compiles the composed step's per-device body —
    # chunk slicing, masked-psum reassembly, kernel-mode match_vma casts
    # in a 2-D manual context — none of which the CPU suite can reach
    # (interpret-mode pallas can't propagate vma).  Trajectory must land
    # on the plain step's (multi-chip layout is pinned on the virtual
    # mesh; the kernels' arithmetic is what needs the chip).
    print("make_dp_sp_train_step 1x1 mesh, pallas chunks (manual mode)")
    from jax.sharding import Mesh

    from hfrep_tpu.parallel.dp_sp import make_dp_sp_train_step
    from hfrep_tpu.train.steps import make_train_step

    tcfg = TrainConfig(batch_size=8, n_critic=2, lstm_backend="pallas")
    mesh2d = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    st2, m2 = make_dp_sp_train_step(pair, tcfg, dataset, mesh2d,
                                    controlled_sampling=True)(
        init_gan_state(jax.random.PRNGKey(6), mcfg, tcfg, pair),
        jax.random.PRNGKey(7))
    pst, pm = jax.jit(make_train_step(pair, tcfg, dataset))(
        init_gan_state(jax.random.PRNGKey(6), mcfg, tcfg, pair),
        jax.random.PRNGKey(7))
    check("dp_sp manual-pallas d_loss", m2["d_loss"], pm["d_loss"], 1e-3)
    check("dp_sp manual-pallas g_params", leaf(st2), leaf(pst), 1e-3)


def section_speed(mesh, sp_lstm):
    """Long-window generator traversal, chunk kernels vs scan."""
    print("sp long-window speed probe (W=480, H=100, B=8, 1 device)")
    wl, hh, bb2 = 480, 100, 8
    kp = jax.random.split(jax.random.fold_in(KEY, 99), 3)
    kern2 = 0.3 * jax.random.normal(kp[0], (hh, 4 * hh))
    recu2 = 0.3 * jax.random.normal(kp[1], (hh, 4 * hh))
    bias2 = 0.1 * jax.random.normal(kp[2], (4 * hh,))

    def timed(be, n=20):
        f = jax.jit(lambda x: sp_lstm(kern2, recu2, bias2, x, mesh,
                                      activation="sigmoid", backend=be))
        x0 = jax.random.normal(jax.random.fold_in(KEY, 100), (bb2, wl, hh))
        jax.block_until_ready(f(x0))
        xs = [jax.random.normal(jax.random.fold_in(KEY, 101 + i),
                                (bb2, wl, hh)) for i in range(n)]
        t0 = timeline.clock()
        for x1 in xs:                 # distinct inputs: tunnel dedupes
            r = f(x1)
        jax.block_until_ready(r)
        return (timeline.clock() - t0) / n

    t_xla, t_pal = timed("xla"), timed("pallas")
    print(f"  xla {t_xla*1e3:.2f} ms  pallas {t_pal*1e3:.2f} ms  "
          f"speedup {t_xla/t_pal:.2f}x")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--section", default="all",
                    choices=["all", "oracle", "sp", "train", "speed"])
    section = ap.parse_args().section
    run = lambda name: section in ("all", name)

    from hfrep_tpu.parallel.mesh import make_mesh
    from hfrep_tpu.parallel.sequence import sp_lstm

    mesh = make_mesh()
    if run("oracle"):
        section_oracle()
    if run("sp"):
        section_sp(mesh, sp_lstm)
    if run("train"):
        section_train(mesh)
    if run("speed"):
        section_speed(mesh, sp_lstm)
    print("ALL OK")


if __name__ == "__main__":
    main()
