"""Train all six GAN families from scratch on chip and score each with
the 12-metric suite vs the real windows — the producer of
``results/family_eval.json`` (RESULTS.md "All six families" table; the
reference's model-selection experiment, ``README.md:8`` + the six
``GAN/*.py`` ``__main__`` blocks at 5000 epochs / batch 32).
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax


def main(out="results/family_eval.json"):

    from hfrep_tpu.config import get_preset
    from hfrep_tpu.core.data import build_gan_dataset, load_panel
    from hfrep_tpu.metrics.gan_eval import GanEval
    from hfrep_tpu.train.trainer import GanTrainer

    panel = load_panel()
    results = {}
    for preset in ("gan_1k", "wgan", "wgan_gp", "mtss_gan", "mtss_wgan",
                   "mtss_wgan_gp"):
        cfg = get_preset(preset)
        ds = build_gan_dataset(cfg.data, jax.random.PRNGKey(cfg.data.seed), panel)
        tr = GanTrainer(cfg, ds)
        t0 = time.perf_counter()
        tr.train()
        wall = time.perf_counter() - t0
        n = min(500, ds.windows.shape[0])
        fake = tr.generate(jax.random.PRNGKey(11), n, unscale=False)
        suite = GanEval(ds.windows[:n], fake, ds.windows,
                        model_name=[cfg.model.family])
        res = suite.run_all()
        res["train_wall_s"] = round(wall, 2)
        res["epochs"] = tr.epoch
        results[cfg.model.family] = res
        print(f"{cfg.model.family}: {tr.epoch} epochs in {wall:.1f}s  "
              f"FID={res.get('FID'):.4g}  JS={res.get('js_div'):.4g}",
              flush=True)

    if os.path.dirname(out):
        os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(results, f, indent=2, default=str)
    print(f"wrote {out}")


if __name__ == "__main__":
    main(*sys.argv[1:])
