"""Train all six GAN families from scratch on chip and score each with
the 12-metric suite vs the real windows — the producer of
``results/family_eval.json`` (RESULTS.md "All six families" table; the
reference's model-selection experiment, ``README.md:8`` + the six
``GAN/*.py`` ``__main__`` blocks at 5000 epochs / batch 32).
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
from hfrep_tpu.obs import timeline

import jax


def main(out="results/family_eval.json", seeds: int = 1):
    """``seeds > 1`` trains that many member-exact models per family in
    ONE vmapped program (`hfrep_tpu/train/multi_seed.py`) and reports
    per-seed metrics plus mean/std — the seed-variance protocol with K×
    fewer dispatches (throughput itself is not improved by vmapping;
    RESULTS.md "Multi-seed vmapped training: measured negative result")."""
    seeds = int(seeds)

    from hfrep_tpu.config import get_preset
    from hfrep_tpu.core.data import build_gan_dataset, load_panel
    from hfrep_tpu.metrics.gan_eval import GanEval
    from hfrep_tpu.train.trainer import GanTrainer

    panel = load_panel()
    results = {}
    for preset in ("gan_1k", "wgan", "wgan_gp", "mtss_gan", "mtss_wgan",
                   "mtss_wgan_gp"):
        cfg = get_preset(preset)
        ds = build_gan_dataset(cfg.data, jax.random.PRNGKey(cfg.data.seed), panel)
        n = min(500, ds.windows.shape[0])
        t0 = timeline.clock()
        if seeds == 1:
            tr = GanTrainer(cfg, ds)
            tr.train()
            wall = timeline.clock() - t0
            fakes = [tr.generate(jax.random.PRNGKey(11), n, unscale=False)]
            epochs = tr.epoch
        else:
            from hfrep_tpu.train.multi_seed import MultiSeedTrainer
            # "auto": seed-sharded over the largest divisor of K that fits
            # the host's devices (linear aggregate scaling, K/n members
            # vmapped per device); vmap row-packing when no mesh fits (the
            # single-chip case here — measured 0.21x/model at K=4).
            mst = MultiSeedTrainer(cfg, ds,
                                   [cfg.train.seed + k for k in range(seeds)],
                                   mesh="auto")
            mst.train()
            wall = timeline.clock() - t0
            cube = mst.generate(jax.random.PRNGKey(11), n, unscale=False)
            fakes = [cube[k] for k in range(seeds)]
            epochs = mst.epoch
        per_seed = []
        for fake in fakes:
            suite = GanEval(ds.windows[:n], fake, ds.windows,
                            model_name=[cfg.model.family])
            per_seed.append(suite.run_all())
        if seeds == 1:
            res = dict(per_seed[0])
        else:
            import numpy as np
            # bool is an int subclass — exclude it so flag-like metrics
            # don't average into meaningless means; nan-aware moments so
            # one non-finite seed can't silently poison a metric (it is
            # flagged instead).
            scalars = [k for k, v in per_seed[0].items()
                       if isinstance(v, (int, float))
                       and not isinstance(v, bool)]
            vals = {k: np.asarray([p[k] for p in per_seed], dtype=float)
                    for k in scalars}
            res = {k: float(np.nanmean(v)) for k, v in vals.items()}
            res["per_seed"] = per_seed
            res["std"] = {k: float(np.nanstd(v)) for k, v in vals.items()}
            nonfinite = {k: int(np.sum(~np.isfinite(v)))
                         for k, v in vals.items() if not np.isfinite(v).all()}
            if nonfinite:
                res["nonfinite_seed_count"] = nonfinite
        res["train_wall_s"] = round(wall, 2)
        res["epochs"] = epochs
        res["n_seeds"] = seeds
        results[cfg.model.family] = res
        print(f"{cfg.model.family}: {epochs} epochs ×{seeds} seed(s) in "
              f"{wall:.1f}s  FID={res.get('FID'):.4g}  "
              f"JS={res.get('js_div'):.4g}", flush=True)

    if os.path.dirname(out):
        os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(results, f, indent=2, default=str)
    print(f"wrote {out}")


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out", nargs="?", default="results/family_eval.json")
    ap.add_argument("--seeds", type=int, default=1,
                    help="models per family, trained member-exact in one "
                         "vmapped program (hfrep_tpu/train/multi_seed.py)")
    a = ap.parse_args()
    main(a.out, a.seeds)
