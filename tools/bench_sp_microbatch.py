"""Validate `sp_microbatch_plan`'s core assumption on the real chip.

The analytic M-vs-Bm model (hfrep_tpu/parallel/sequence.py) rests on one
measurable claim: at these shapes the recurrence superstep cost is
LATENCY-bound — flat in the microbatch row count Bm — so total sp time
scales with the superstep count (M+D−1)·W/D, not with rows.  On this
host D=1, where supersteps = M·W: the model predicts time ∝ M with Bm
halving having no offsetting benefit.  Measuring the full sp train epoch
at M ∈ {1, 2, 4} tests exactly that (any work-bound component would bend
the curve below linear).

Same methodology as every round-3+ number: 50-epoch scanned blocks, two
warmups, distinct keys per call.
"""

import os
import sys
from hfrep_tpu.obs import timeline

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def main(microbatches=(1, 2, 4), n_calls=6):
    from hfrep_tpu.config import ModelConfig, TrainConfig
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.parallel.sequence import make_sp_multi_step
    from hfrep_tpu.train.states import init_gan_state

    mcfg = ModelConfig(family="mtss_wgan_gp", hidden=100, window=168,
                       features=36)
    tcfg = TrainConfig(batch_size=32, n_critic=5, steps_per_call=50)
    data = jax.random.uniform(jax.random.PRNGKey(1), (256, 168, 36),
                              jnp.float32)
    pair = build_gan(mcfg)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("sp",))
    base = None
    for m in microbatches:
        step = make_sp_multi_step(pair, tcfg, data, mesh, microbatches=m)
        state = init_gan_state(jax.random.PRNGKey(0), mcfg, tcfg, pair)
        # Two warmups (compile + donated-state retrace); keys are salted
        # by M so no (program, inputs) pair ever repeats across configs —
        # the tunneled backend dedupes identical executions server-side.
        state, mm = step(state, jax.random.fold_in(jax.random.PRNGKey(1), m))
        float(jax.device_get(mm["d_loss"])[-1])
        state, mm = step(state, jax.random.fold_in(jax.random.PRNGKey(99), m))
        float(jax.device_get(mm["d_loss"])[-1])
        trials = []
        for t in range(2):                     # back-to-back agreement check
            t0 = timeline.clock()
            for i in range(n_calls):
                state, mm = step(state, jax.random.fold_in(
                    jax.random.PRNGKey(2 + 1000 * m + t), i))
            # device_get is the fence: block_until_ready does not
            # reliably fence on this backend (RESULTS.md), but the calls
            # are state-threaded, so materializing the last metrics
            # forces the whole chain.
            last = float(jax.device_get(mm["d_loss"])[-1])
            trials.append((timeline.clock() - t0) / (n_calls * 50) * 1e3)
            assert last == last, "non-finite loss"
        ms = min(trials)
        base = base or ms
        print(f"M={m} (Bm={32 // m}): {ms:.2f} ms/epoch (trials "
              f"{', '.join(f'{v:.2f}' for v in trials)}) "
              f"({ms / base:.2f}x vs M=1; latency model predicts {m:.2f}x)",
              flush=True)


if __name__ == "__main__":
    main()
