"""Multi-seed envelope of the real-only latent sweep (VERDICT r4 item 1b).

The published real-only results (`autoencoder_v4.ipynb` cells 13/32 via
BASELINE.md) are one draw of a 420-training experiment: best-OOS-R²
latent 21 (mean 0.681, max 0.835) and a low-latent-dominant ex-post
Sharpe pattern (10/13 strategies best at latent 2, Sharpe 0.68-0.69).
This tool reruns the ENTIRE sweep for S seeds — S x 21 trainings as one
vmapped XLA program — and reports the envelope, so the published draw
can be located inside (or outside) run-to-run variance.

Usage: python tools/seed_envelope.py [--seeds 24] [--out results/seed_envelope]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from hfrep_tpu.obs import timeline
from hfrep_tpu.config import AEConfig
from hfrep_tpu.core.data import load_panel
from hfrep_tpu.models.autoencoder import latent_mask
from hfrep_tpu.replication.engine import (
    ReplicationEngine, sweep_autoencoders, sweep_evaluate,
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=24)
    ap.add_argument("--cleaned-dir", default="/root/reference/cleaned_data")
    ap.add_argument("--out", default="results/seed_envelope")
    ap.add_argument("--lr", type=float, default=None, help="AEConfig.lr override")
    args = ap.parse_args()

    panel = load_panel(args.cleaned_dir)
    x_train, x_test, y_train, y_test = panel.train_test_split()
    rf_test = panel.rf[x_train.shape[0]:]

    cfg = AEConfig()
    if args.lr is not None:
        cfg = dataclasses.replace(cfg, lr=args.lr)
    dims = list(range(1, 22))
    max_latent = max(dims)
    cfg = dataclasses.replace(cfg, latent_dim=max_latent)

    engine = ReplicationEngine(x_train, y_train, x_test, y_test, cfg)
    masks = jnp.stack([latent_mask(d, max_latent) for d in dims])
    rf_j = jnp.asarray(rf_test, jnp.float32)
    factor_j = jnp.asarray(panel.factors, jnp.float32)

    # One program: vmap over seeds of (vmap over latents of train).
    train_all = jax.jit(jax.vmap(
        lambda k: sweep_autoencoders(k, engine.x_train, cfg, dims)))
    # Evaluation compiled once, applied per seed (keeps peak memory flat).
    eval_fn = jax.jit(lambda p, m: sweep_evaluate(
        engine.model, cfg, engine.x_train, engine.x_test, engine.y_test,
        rf_j, factor_j, p, m))

    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(args.seeds)])
    t0 = timeline.clock()
    swept = jax.block_until_ready(train_all(keys))
    t_train = timeline.clock() - t0

    rows = []
    for s in range(args.seeds):
        params_s = jax.tree_util.tree_map(lambda a: a[s], swept.params)
        ev = jax.device_get(eval_fn(params_s, masks))
        oos_mean = ev["oos_r2"].mean(axis=1)            # (L,)
        i_best = int(np.argmax(oos_mean))
        sharpe_post = ev["sharpe_post"]                 # (L, S)
        best_lat = np.argmax(sharpe_post, axis=0)       # (S,) index into dims
        to = ev["turnover"]                             # (L, S)
        rows.append({
            "turnover_latent2": [float(v) for v in to[dims.index(2)]],
            "turnover_latent7": [float(v) for v in to[dims.index(7)]],
            "seed": s,
            "best_oos_latent": dims[i_best],
            "best_oos_mean": float(oos_mean[i_best]),
            "best_oos_max": float(ev["oos_r2"][i_best].max()),
            "oos_mean_latent21": float(oos_mean[dims.index(21)]),
            "oos_max_latent21": float(ev["oos_r2"][dims.index(21)].max()),
            "is_r2_latent21": float(ev["is_r2"][dims.index(21)]),
            "best_latent_by_strategy": [int(dims[i]) for i in best_lat],
            "best_sharpe_by_strategy": [float(sharpe_post[i, j])
                                        for j, i in enumerate(best_lat)],
        })
        print(f"seed {s}: best latent {rows[-1]['best_oos_latent']} "
              f"mean {rows[-1]['best_oos_mean']:.3f} "
              f"max {rows[-1]['best_oos_max']:.3f} "
              f"L21 {rows[-1]['oos_mean_latent21']:.3f}", flush=True)

    names = panel.hf_names
    l21_mean = np.array([r["oos_mean_latent21"] for r in rows])
    l21_max = np.array([r["oos_max_latent21"] for r in rows])
    best_mean = np.array([r["best_oos_mean"] for r in rows])
    best_lat_arr = np.array([r["best_oos_latent"] for r in rows])
    sh = np.array([r["best_sharpe_by_strategy"] for r in rows])   # (S, 13)
    bl = np.array([r["best_latent_by_strategy"] for r in rows])   # (S, 13)
    # how many strategies share one best latent per seed (published: 10/13 at 2)
    dom = np.array([np.bincount(b).max() for b in bl])
    # the dominant-latent cluster's Sharpes per seed (the published
    # analogue is the 10-strategy latent-2 band 0.637-0.691)
    dom_cluster = [sh[i][bl[i] == np.bincount(bl[i]).argmax()]
                   for i in range(len(rows))]
    dom_sharpe_lo = np.array([c.min() for c in dom_cluster])
    dom_sharpe_hi = np.array([c.max() for c in dom_cluster])

    def env(a):
        return {"min": float(a.min()), "p25": float(np.percentile(a, 25)),
                "median": float(np.median(a)), "p75": float(np.percentile(a, 75)),
                "max": float(a.max())}

    published = {"oos_mean_latent21": 0.681, "oos_max_latent21": 0.835,
                 "is_r2_latent21": 0.889, "best_oos_latent": 21,
                 "dominant_latent_count": 10, "dominant_sharpe_band": [0.637, 0.691],
                 "turnover_latent2_range": [2.274, 8.227],   # cell 33
                 "turnover_latent7_range": [3.801, 50.801]}  # cell 34
    to2 = np.array([r["turnover_latent2"] for r in rows])    # (S, 13)
    to7 = np.array([r["turnover_latent7"] for r in rows])
    summary = {
        "n_seeds": args.seeds,
        "lr": cfg.lr,
        "train_seconds": t_train,
        "published": published,
        "envelope": {
            "best_oos_latent_counts": {int(k): int(v) for k, v in
                                       zip(*np.unique(best_lat_arr, return_counts=True))},
            "best_oos_mean": env(best_mean),
            "oos_mean_latent21": env(l21_mean),
            "oos_max_latent21": env(l21_max),
            "is_r2_latent21": env(np.array([r["is_r2_latent21"] for r in rows])),
            "dominant_latent_count": env(dom.astype(float)),
            "dominant_cluster_sharpe_lo": env(dom_sharpe_lo),
            "dominant_cluster_sharpe_hi": env(dom_sharpe_hi),
            "per_strategy_best_sharpe": {
                names[j]: env(sh[:, j]) for j in range(len(names))},
            "turnover_latent2_min": env(to2.min(axis=1)),
            "turnover_latent2_max": env(to2.max(axis=1)),
            "turnover_latent7_min": env(to7.min(axis=1)),
            "turnover_latent7_max": env(to7.max(axis=1)),
        },
        "published_inside": {
            "oos_mean_latent21": bool(l21_mean.min() <= 0.681 <= l21_mean.max()),
            "oos_max_latent21": bool(l21_max.min() <= 0.835 <= l21_max.max()),
            "best_latent_is_21_fraction": float((best_lat_arr == 21).mean()),
            "dominant_pattern_fraction": float((dom >= 8).mean()),
            # published turnover table (cell 33/34) inside the per-seed
            # range envelope at the same latent
            "turnover_latent2_min": bool(to2.min(axis=1).min() <= 2.274
                                         <= to2.min(axis=1).max()),
            "turnover_latent2_max": bool(to2.max(axis=1).min() <= 8.227
                                         <= to2.max(axis=1).max()),
            "turnover_latent7_min": bool(to7.min(axis=1).min() <= 3.801
                                         <= to7.min(axis=1).max()),
            "turnover_latent7_max": bool(to7.max(axis=1).min() <= 50.801
                                         <= to7.max(axis=1).max()),
        },
        "rows": rows,
    }
    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "envelope.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({k: summary[k] for k in
                      ("published", "published_inside")}, indent=2))
    print(json.dumps(summary["envelope"]["oos_mean_latent21"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
