"""Shim: the sequence-parallel gap staging probe folded into the
consolidated perf probe (ISSUE 13) — one profiling instrument on the
``hfrep_tpu.obs.attrib`` layer.  Kept so RESULTS.md's historical
command lines keep working; use ``tools/perf_probe.py sp`` directly.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perf_probe import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main(["sp"] + sys.argv[1:]))
