"""Locate where the single-device sequence-parallel step's ~100× gap vs
the plain step comes from (RESULTS.md "Sequence-parallel pallas chunks"
honest-bounds note).

Stages, each state-threaded (the only trustworthy timing through the
tunnel — see RESULTS.md round-3 addendum) and chained `reps`× inside one
jitted dispatch:

  fwd        critic forward only
  grad       1st-order grad of a critic scalar loss (the critic-update path)
  gp2        grad-of-grad (the gradient-penalty second-order path)

run: python tools/sp_profile_probe.py [--reps 20] [--backend xla|pallas]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hfrep_tpu.config import ModelConfig
from hfrep_tpu.models.registry import build_gan
from hfrep_tpu.parallel.mesh import make_mesh
from hfrep_tpu.parallel.sequence import sp_critic


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--backend", default="xla", choices=["xla", "pallas"])
    args = ap.parse_args()
    reps = args.reps

    mesh = make_mesh()
    mcfg = ModelConfig(family="mtss_wgan_gp", hidden=100, window=168,
                       features=36)
    pair = build_gan(mcfg)
    key = jax.random.PRNGKey(0)
    x = jax.random.uniform(key, (32, 168, 36))
    d_params = pair.discriminator.init(key, x)["params"]
    be = args.backend

    def plain_apply(p, xx):
        return pair.discriminator.apply({"params": p}, xx, backend=be)

    def sp_apply(p, xx):
        return sp_critic(p, xx, mesh, backend=be)

    def chain(stage, apply):
        """One dispatch = `reps` data-dependent repetitions of `stage`."""
        def scalar(p, xx):
            return jnp.sum(apply(p, xx) ** 2)

        if stage == "fwd":
            unit = lambda p, xx: jnp.sum(apply(p, xx))
        elif stage == "grad":
            unit = lambda p, xx: sum(jnp.sum(t) for t in jax.tree_util.tree_leaves(
                jax.grad(scalar)(p, xx)))
        else:  # gp2: d/dp of ||grad_x scalar||² — the GP second-order shape
            def gp(p, xx):
                g = jax.grad(scalar, argnums=1)(p, xx)
                return jnp.sum(g ** 2)
            unit = lambda p, xx: sum(jnp.sum(t) for t in jax.tree_util.tree_leaves(
                jax.grad(gp)(p, xx)))

        def run(p, xx):
            def body(c, _):
                v = unit(p, xx + 1e-9 * c)     # data dependence across reps
                return v.astype(jnp.float32), None
            out, _ = jax.lax.scan(body, jnp.float32(0), None, length=reps)
            return out

        return jax.jit(run)

    for stage in ("fwd", "grad", "gp2"):
        row = {}
        for name, apply in (("plain", plain_apply), ("sp", sp_apply)):
            f = chain(stage, apply)
            t_c0 = time.perf_counter()
            float(f(d_params, x))                       # compile + run
            compile_s = time.perf_counter() - t_c0
            t0 = time.perf_counter()
            float(f(d_params, x * 1.0001))
            row[name] = (time.perf_counter() - t0) / reps
            print(f"  {stage:4s} {name:5s}: {row[name]*1e3:8.2f} ms/unit "
                  f"(compile {compile_s:.0f}s)")
        print(f"{stage}: sp/plain = {row['sp']/row['plain']:.1f}x")


if __name__ == "__main__":
    main()
