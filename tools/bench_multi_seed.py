"""Per-model throughput of K-seed vmapped training vs K=1 (the roofline
conversion RESULTS.md's batch table predicts: ~1.8× per-sample at 128
MXU rows).  Flagship MTSS-WGAN-GP at the reference's (48, 35) shape and
batch 32 per member — member semantics untouched, only the number of
models per program varies.

Run on the real chip: `python tools/bench_multi_seed.py [K ...]`
(default 1 2 4).  Uses bench.py's measurement discipline: one jitted
50-epoch block per dispatch, distinct keys per call (the tunneled
backend dedupes identical executions).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hfrep_tpu.config import ModelConfig, TrainConfig


def measure(n_seeds: int, n_calls: int = 10) -> float:
    from bench import load_dataset
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.multi_seed import (init_multi_seed_states,
                                            make_multi_seed_step)

    mcfg = ModelConfig(family="mtss_wgan_gp")
    tcfg = TrainConfig(steps_per_call=50)
    dataset = load_dataset(mcfg, include_rf=False)
    pair = build_gan(mcfg)
    keys = jnp.stack([jax.random.PRNGKey(100 + s) for s in range(n_seeds)])
    states = init_multi_seed_states(keys, mcfg, tcfg, pair)
    fn = make_multi_seed_step(pair, tcfg, dataset)

    run_keys = jnp.stack([jax.random.PRNGKey(s) for s in range(n_seeds)])
    fold = jax.jit(jax.vmap(jax.random.fold_in, in_axes=(0, None)))
    states, metrics = fn(states, fold(run_keys, 0))      # compile + warm
    jax.block_until_ready(metrics)
    t0 = time.perf_counter()
    for i in range(1, n_calls + 1):
        states, metrics = fn(states, fold(run_keys, i))
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0
    assert jnp.isfinite(metrics["d_loss"]).all()
    assert jnp.isfinite(metrics["g_loss"]).all()
    # model-epochs per second (each member advances 50 epochs per call)
    return n_calls * tcfg.steps_per_call * n_seeds / dt


def main(argv):
    ks = [int(a) for a in argv] or [1, 2, 4]
    base = None
    for k in ks:
        rate = measure(k)
        if base is None:
            base = rate / k               # per-model rate at the first K
        print(f"K={k}: {rate:8.1f} model-epochs/s  "
              f"({rate / k:7.1f} per model, {rate / k / base:4.2f}x vs K={ks[0]})",
              flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
