"""Per-model throughput of K-seed vmapped training vs K=1 (the roofline
conversion RESULTS.md's batch table predicts: ~1.8× per-sample at 128
MXU rows).  Flagship MTSS-WGAN-GP at the reference's (48, 35) shape and
batch 32 per member — member semantics untouched, only the number of
models per program varies.

Run on the real chip: `python tools/bench_multi_seed.py [K ...]`
(default 1 2 4).  Uses bench.py's measurement discipline: one jitted
50-epoch block per dispatch, distinct keys per call (the tunneled
backend dedupes identical executions).  Pass ``--obs-dir DIR`` to emit
the run through :mod:`hfrep_tpu.obs` (manifest + block spans + per-K
gauges + memory snapshots) so two bench runs diff machine-readably with
``python -m hfrep_tpu.obs report A B``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hfrep_tpu.config import ModelConfig, TrainConfig
from hfrep_tpu.obs import get_obs, timeline


def measure(n_seeds: int, n_calls: int = 10) -> float:
    from bench import load_dataset
    from hfrep_tpu.models.registry import build_gan
    from hfrep_tpu.train.multi_seed import (init_multi_seed_states,
                                            make_multi_seed_step)

    obs = get_obs()
    mcfg = ModelConfig(family="mtss_wgan_gp")
    tcfg = TrainConfig(steps_per_call=50)
    dataset = load_dataset(mcfg, include_rf=False)
    pair = build_gan(mcfg)
    keys = jnp.stack([jax.random.PRNGKey(100 + s) for s in range(n_seeds)])
    states = init_multi_seed_states(keys, mcfg, tcfg, pair)
    fn = make_multi_seed_step(pair, tcfg, dataset)

    run_keys = jnp.stack([jax.random.PRNGKey(s) for s in range(n_seeds)])
    fold = jax.jit(jax.vmap(jax.random.fold_in, in_axes=(0, None)))
    t0 = timeline.clock()
    states, metrics = fn(states, fold(run_keys, 0))      # compile + warm
    jax.block_until_ready(metrics)
    obs.record_span("block", timeline.clock() - t0,
                    steps=tcfg.steps_per_call, warmup=True, synced=True,
                    n_seeds=n_seeds)
    t0 = timeline.clock()
    for i in range(1, n_calls + 1):
        states, metrics = fn(states, fold(run_keys, i))
    jax.block_until_ready(metrics)
    dt = timeline.clock() - t0
    obs.record_span("block", dt, steps=n_calls * tcfg.steps_per_call,
                    warmup=False, synced=True, n_seeds=n_seeds)
    assert jnp.isfinite(metrics["d_loss"]).all()
    assert jnp.isfinite(metrics["g_loss"]).all()
    # model-epochs per second (each member advances 50 epochs per call)
    return n_calls * tcfg.steps_per_call * n_seeds / dt


def main(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("ks", nargs="*", type=int, default=None,
                    help="member counts to measure (default: 1 2 4)")
    ap.add_argument("--obs-dir", default=None,
                    help="emit through hfrep_tpu.obs into this run dir")
    args = ap.parse_args(argv)
    ks = args.ks or [1, 2, 4]
    import hfrep_tpu.obs as obs_pkg
    with obs_pkg.session(args.obs_dir, command="bench_multi_seed",
                         ks=ks) as obs:
        base = None
        for k in ks:
            rate = measure(k)
            if base is None:
                base = rate / k           # per-model rate at the first K
            obs.gauge(f"bench/K{k}/model_epochs_per_sec").set(
                rate, per_model=rate / k, vs_first=rate / k / base)
            print(f"K={k}: {rate:8.1f} model-epochs/s  ({rate / k:7.1f} "
                  f"per model, {rate / k / base:4.2f}x vs K={ks[0]})",
                  flush=True)
        obs.memory_snapshot(phase="bench_end")


if __name__ == "__main__":
    main(sys.argv[1:])
