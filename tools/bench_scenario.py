"""Scenario-factory probe: where does the padded fabric actually break?

Drives synthetic universes (F funds × M months) through the walk-forward
sweep fabric and conditional bank generation, and *measures* the
structural numbers the ROADMAP's scale claims rest on:

* ``scenario/lanes`` — the (window × latent) grid trained as ONE padded
  program;
* ``scenario/pad_waste_frac`` — the fraction of the padded cube that is
  zero rows (what ragged expanding windows cost);
* ``scenario/windows_per_sec`` — walk-forward throughput end to end
  (train + score);
* ``scenario/bank_windows_per_sec`` — conditional sampling throughput.

``--self-test`` (wired into ``tools/check.sh``, env-stripped) is the CI
fast path: a small universe, the bank determinism replay (same
seed+regime ⇒ identical ``aggregate_digest``, re-derived in memory), and
the walk-forward ≥100-lane preempt→resume bit-identity drill (injected
``preempt`` at a chunk boundary and at a window boundary; the resumed
surface must match an undisturbed run byte for byte).

Prints ONE JSON line.  Exit 0 = self-checks passed, 1 = a check (or a
history regression) failed, 2 = tooling failure.

Telemetry: with ``HFREP_OBS_DIR`` the run annotates a ``scenario``
config section, so the history store indexes it under the scenario
comparability key (``scnf<funds>m<months>w<windows>l<latents>``) — a
universe drive's windows/sec series never blends into a GAN training
steps/sec series.  With a history store on top, the run gates against
the rolling baseline and auto-ingests on pass, exactly like ``bench.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

if __name__ == "__main__":               # `python tools/bench_scenario.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

from hfrep_tpu.obs import timeline
import hfrep_tpu.obs as obs_pkg


def _bank_check(problems: list, feats: int, window: int,
                blocks: int, block_size: int) -> dict:
    """Bank determinism: generate, replay one block's digest in memory,
    regenerate into a second directory — three independent derivations
    of the same bytes must agree."""
    from hfrep_tpu.scenario.conditional import (
        fixture_bundle,
        generate_bank,
        replay_block_digest,
    )

    bundle = fixture_bundle(feats=feats, window=window, n_regimes=3,
                            epochs=2)
    d1 = tempfile.mkdtemp(prefix="scn_bank1_")
    d2 = tempfile.mkdtemp(prefix="scn_bank2_")
    try:
        t0 = timeline.clock()
        m1 = generate_bank(bundle, d1, blocks=blocks,
                           block_size=block_size, stream_seed=5)
        bank_secs = timeline.clock() - t0
        replay = replay_block_digest(bundle, 5, 1, 0, block_size)
        if replay != m1["block_digests"]["r1_00000"]:
            problems.append("bank: in-memory replay digest diverged from "
                            "the published block")
        m2 = generate_bank(bundle, d2, blocks=blocks,
                           block_size=block_size, stream_seed=5)
        if m2["aggregate_digest"] != m1["aggregate_digest"]:
            problems.append("bank: regeneration changed the aggregate "
                            "digest (determinism broken)")
        m3 = generate_bank(bundle, d1, blocks=blocks,
                           block_size=block_size, stream_seed=5)
        if m3["generated"] != 0:
            problems.append(f"bank: re-run regenerated {m3['generated']} "
                            "verified blocks (idempotence broken)")
        n_windows = 3 * blocks * block_size
        return {"aggregate_digest": m1["aggregate_digest"],
                "bank_secs": round(bank_secs, 3),
                "bank_windows_per_sec": round(n_windows
                                              / max(bank_secs, 1e-9), 3)}
    finally:
        shutil.rmtree(d1, ignore_errors=True)
        shutil.rmtree(d2, ignore_errors=True)


def _resume_check(problems: list, spec, cfg, latents,
                  x, y, rf) -> dict:
    """The walk-forward SIGTERM→resume bit-identity drill: an
    uninterrupted reference run, then a run hit by a REAL SIGTERM at a
    training chunk boundary (the ``sigterm`` fault kind fires the actual
    signal through the graceful-drain handler) and a signal-free preempt
    at a scoring window boundary, resumed to completion — final surfaces
    must match byte for byte."""
    import hfrep_tpu.resilience as res
    from hfrep_tpu.resilience.faults import FaultPlan
    from hfrep_tpu.scenario.walkforward import run_walkforward

    base = tempfile.mkdtemp(prefix="scn_wf_base_")
    other = tempfile.mkdtemp(prefix="scn_wf_resume_")
    try:
        ref = run_walkforward(x, y, rf, spec, cfg, latents, base)
        preempts = 0
        for plan in ("sigterm@chunk=2", "preempt@window=2"):
            res.install_plan(FaultPlan.parse(plan))
            try:
                run_walkforward(x, y, rf, spec, cfg, latents, other,
                                resume=True)
                problems.append(f"resume: injected {plan} did not preempt")
            except res.Preempted:
                preempts += 1
            finally:
                res.clear_plan()
        final = run_walkforward(x, y, rf, spec, cfg, latents, other,
                                resume=True)
        for f in ("walkforward.json", "walkforward.csv",
                  "walkforward_ante.csv"):
            a = open(os.path.join(base, f), "rb").read()
            b = open(os.path.join(other, f), "rb").read()
            if a != b:
                problems.append(f"resume: {f} differs from the "
                                "undisturbed run")
        lanes = spec.n_windows * len(latents)
        if final["stats"]["lanes"] != lanes:
            problems.append(f"resume: lanes {final['stats']['lanes']} != "
                            f"expected {lanes}")
        if not np.isfinite(ref["surface_post"]).all():
            problems.append("resume: reference surface carries non-finite "
                            "scores")
        return {"preempts": preempts, "lanes": lanes,
                "ref_stats": ref["stats"]}
    finally:
        shutil.rmtree(base, ignore_errors=True)
        shutil.rmtree(other, ignore_errors=True)


def run_probe(obs, self_test: bool) -> int:
    from hfrep_tpu.config import AEConfig
    from hfrep_tpu.scenario.universe import (
        UniverseSpec,
        drive_universe,
        synthesize_universe,
    )
    from hfrep_tpu.scenario.walkforward import WalkForwardSpec

    problems: list = []
    doc: dict = {"metric": "scenario", "self_test": bool(self_test)}

    if self_test:
        # ≥100 lanes as one padded drive — the acceptance floor — at
        # fixture shapes: 25 expanding windows × 4 latent lanes
        uspec = UniverseSpec(funds=8, months=96, n_factors=6, seed=3)
        spec = WalkForwardSpec(start=30, n_windows=25, horizon=10, step=2)
        latents = [1, 2, 3, 4]
        cfg = AEConfig(epochs=6, batch_size=16, chunk_epochs=3,
                       ols_window=6, patience=2)
        bank_args = dict(feats=6, window=12, blocks=2, block_size=4)
    else:
        uspec = UniverseSpec(funds=64, months=480, n_factors=22, seed=3)
        spec = WalkForwardSpec(start=240, n_windows=48, horizon=60,
                               step=4)
        latents = list(range(1, 9))
        cfg = AEConfig(epochs=200, chunk_epochs=50)
        bank_args = dict(feats=22, window=24, blocks=4, block_size=32)

    # the scenario comparability key: this drive's windows/sec can never
    # blend into a training steps/sec series (the svb* pattern)
    obs.annotate(config={"scenario": {
        "funds": uspec.funds, "months": uspec.months,
        "windows": spec.n_windows, "latents": len(latents)}})

    # universe determinism (same spec ⇒ same bytes)
    u1 = synthesize_universe(uspec)
    u2 = synthesize_universe(uspec)
    if not all(np.array_equal(a, b) for a, b in zip(u1, u2)):
        problems.append("universe: synthesis is not deterministic")

    doc["bank"] = _bank_check(problems, **bank_args)

    u = u1
    if self_test:
        doc["walkforward"] = _resume_check(problems, spec, cfg, latents,
                                           u.factors, u.hfd, u.rf)
        stats = doc["walkforward"]["ref_stats"]
    else:
        out = tempfile.mkdtemp(prefix="scn_wf_bench_")
        try:
            stats = drive_universe(uspec, spec, cfg, latents, out)["stats"]
        finally:
            shutil.rmtree(out, ignore_errors=True)
        doc["walkforward"] = {"stats": stats}

    lanes = spec.n_windows * len(latents)
    if lanes < 100:
        problems.append(f"config: only {lanes} lanes (< 100 floor)")
    if not 0.0 <= stats["pad_waste_frac"] < 1.0:
        problems.append(f"pad_waste_frac {stats['pad_waste_frac']} "
                        "outside [0, 1)")
    for name, value in (
            ("scenario/lanes", stats["lanes"]),
            ("scenario/pad_waste_frac", stats["pad_waste_frac"]),
            ("scenario/windows_per_sec", stats["windows_per_sec"]),
            ("scenario/bank_windows_per_sec",
             doc["bank"]["bank_windows_per_sec"])):
        if value is not None and np.isfinite(value):
            obs.gauge(name).set(float(value))
    obs.memory_snapshot(phase="bench_scenario_end")

    doc["self_check"] = "ok" if not problems else "; ".join(problems)
    print(json.dumps(doc, default=str))
    if problems:
        print(f"bench_scenario: SELF-CHECK FAILED: {'; '.join(problems)}",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_scenario",
        description="scenario-factory probe: padded walk-forward "
                    "throughput, bank determinism, universe scaling")
    ap.add_argument("--self-test", action="store_true",
                    help="small universe + bank determinism replay + "
                         "the 100-lane walk-forward preempt→resume "
                         "bit-identity drill (the CI fast path)")
    args = ap.parse_args(argv)

    obs_dir = os.environ.get("HFREP_OBS_DIR")
    with obs_pkg.session_or_off(obs_dir, "bench_scenario",
                                command="bench_scenario") as obs:
        if obs_dir and not obs.enabled:
            obs_dir = None               # degraded: nothing to gate below
        rc = run_probe(obs, args.self_test)
    from hfrep_tpu.obs import history as hist_mod
    hist = hist_mod.resolve_history(obs_dir)
    if obs_dir and hist:
        rc = hist_mod.gate_and_ingest(obs_dir, hist, rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
